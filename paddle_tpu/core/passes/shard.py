"""GSPMD-style partitioner pass: sharding specs drive the lowering.

PR-19 made sharding first-class IR state (`Variable.sharding`, the
D017-D021 lints, the memplan HBM planner) but the specs stayed inert:
the executor replicated every parameter and GSPMD inserted whatever
implicit collectives it liked.  This pass is the closing move (ROADMAP
item 1): it turns declared specs into an executed partitioning with
explicit, fused collectives per the memory-efficient array-
redistribution cost model (arxiv 2112.01075).

Runs in the PT_OPT pipeline (after cse, before fuse_elementwise) when a
mesh is declared on the program (`Program.set_mesh_axes`); `PT_SHARD=0`
or `PT_OPT_SKIP=shard` disables it.  Phases, on the root block:

  complete   propagate declared specs forward with the SAME transfer
             rules as the D017/D018 analyzer and write the inferred
             spec onto every unannotated produced var — lint, memplan,
             and the lowering's in/out shardings all see one answer
  zero       ZeRO-style optimizer-state sharding (PT_SHARD_ZERO=1):
             each eligible parameter's accumulators (and, when the
             parameter is only read by the forward + its own update
             op, the parameter storage itself) get the parameter's
             spec additionally sharded over the data axis on dim 0;
             an explicit `all_gather` rejoins the full layout at the
             first forward consumer — only where a consumer demands it
  grads      rewrite the `__backward__` seam: one explicit
             `grad_allreduce` per parameter, dst = the parameter's
             (possibly ZeRO-sharded) spec, so the gradient reduction
             happens exactly once and a ZeRO dst collapses
             all-reduce+scatter into a single reduce-scatter
  reshard    every remaining D018 edge (dataflow delivers one layout,
             the consumer/annotation demands another) materializes as
             an explicit `reshard` op carrying src/dst specs and the
             estimated bytes — the same `_var_bytes` the D018 lint
             reports, so analyzer and rewriter cannot drift
  fuse       adjacent collectives on single-consumer edges collapse to
             one op (reshard-of-reshard; all-gather-then-reduce pairs
             become one grad_allreduce)

Everything the pass inserts stays visible as an explicit op in the
optimized program (collectives are not FUSABLE_OPS), and every kernel
is the identity off-mesh — the same optimized program runs bitwise-
identically on a single device, which is what the parity tests pin.
"""
import os

from ..framework import Parameter
from ..sharding import (normalize_spec, spec_axes, spec_to_jsonable,
                        spec_from_jsonable)

__all__ = ['run', 'enabled', 'active_for', 'zero_enabled', 'zero_axis',
           'plan_zero_specs', 'COLLECTIVE_OPS']

COLLECTIVE_OPS = ('reshard', 'all_gather', 'grad_allreduce')

# optimizer update ops (ops/optimizer_ops.py): Param/Grad in,
# ParamOut out, persistable accumulator state threaded through
_OPT_UPDATE_OPS = {
    'sgd', 'momentum', 'lars_momentum', 'adam', 'adamax', 'adagrad',
    'decayed_adagrad', 'adadelta', 'rmsprop', 'ftrl', 'lamb',
}

_BACKWARD_OP = '__backward__'


def enabled():
    return os.environ.get('PT_SHARD', '1') not in ('0', 'false', 'False')


def zero_enabled():
    return os.environ.get('PT_SHARD_ZERO', '1') not in ('0', 'false',
                                                        'False')


def zero_axis():
    return os.environ.get('PT_SHARD_ZERO_AXIS', 'data')


def config_token():
    """The shard-pass component of passes.config_token(): part of the
    executor's hot key and the launch signature, so flipping PT_SHARD /
    PT_SHARD_ZERO mid-process reads as a named change."""
    if not enabled():
        return ('shard_off',)
    return ('shard_on', 'zero' if zero_enabled() else 'nozero',
            zero_axis())


def active_for(program):
    """Whether the pass will rewrite THIS program: pipeline on, pass not
    skipped, PT_SHARD on, and a mesh declared.  memplan uses this to
    decide whether the ZeRO divisor applies to the per-device plan."""
    from . import enabled as _opt_enabled, skip_set
    return (enabled() and _opt_enabled() and 'shard' not in skip_set()
            and bool(program.mesh_axes()))


# ------------------------------------------------------------ analysis
def _analysis_rules():
    """The D017/D018 analyzer's transfer-rule surface — imported lazily
    (analysis imports core.passes.walker; a top-level import here would
    cycle) and shared so the rewriter cannot drift from the lint."""
    from ...analysis.passes import sharding as az
    return az


def _declared(block, name):
    v = block._find_var_recursive(name)
    return v._sharding_spec if v is not None else None


def _trim(spec):
    """Strip redundant trailing None entries (PartitionSpec semantics)."""
    spec = tuple(spec or ())
    while spec and spec[-1] is None:
        spec = spec[:-1]
    return spec


def _eqspec(a, b):
    """Layout equality up to trailing replication — shared semantics
    with the analyzer's D018 comparison."""
    return _trim(a) == _trim(b)


def _pad(spec, rank):
    """Spec padded with None entries to `rank` (PartitionSpec semantics:
    trailing dims are replicated)."""
    spec = tuple(spec or ())
    if rank is None or len(spec) >= rank:
        return spec
    return spec + (None,) * (rank - len(spec))


class _Propagator(object):
    """Forward spec propagation over the root block with the analyzer's
    transfer functions, plus the collective-op rule (out = dst_spec).
    `on_mismatch(op_index, op, name, have, want, kind)` fires exactly
    where the analyzer would report D018."""

    def __init__(self, program, on_mismatch=None):
        self.az = _analysis_rules()
        self.program = program
        self.block = program.global_block()
        self.env = {}
        self.on_mismatch = on_mismatch or (lambda *a, **k: None)
        for name, v in self.block.vars.items():
            if v._sharding_spec is not None:
                self.env[name] = v._sharding_spec

    def in_spec(self, name):
        if name in self.env:
            return self.env[name]
        return _declared(self.block, name)

    def walk(self):
        for i, op in enumerate(list(self.block.ops)):
            self.step(i, op)
        return self.env

    def step(self, i, op):
        block = self.block
        if op.attrs.get('sub_block') is not None:
            for n in op.output_names():
                self._finish(i, op, n, None)
            return
        if op.type == _BACKWARD_OP:
            pnames = op.attrs.get('params', ())
            for slot, names in op.outputs.items():
                if slot == 'Grads':
                    for p, g in zip(pnames, names):
                        self._finish(i, op, g, self.in_spec(p))
                else:
                    for n in names:
                        self._finish(i, op, n, None)
            return
        out_specs = self._propagate(i, op)
        for n in op.output_names():
            self._finish(i, op, n, out_specs.get(n))

    def _propagate(self, i, op):
        az = self.az
        outs = {}
        first_out = (op.outputs.get('Out') or [None])[0]
        if op.type in COLLECTIVE_OPS:
            if first_out is not None:
                outs[first_out] = normalize_spec(
                    spec_from_jsonable(op.attrs.get('dst_spec')))
            return outs
        if op.type in az._SAME_LAYOUT:
            merged = None
            for slot in ('X', 'Y'):
                for n in op.inputs.get(slot, ()):
                    s = self.in_spec(n)
                    if s is None:
                        continue
                    if merged is None:
                        merged = s
                    elif not _eqspec(s, merged):
                        self.on_mismatch(i, op, n, s, merged, 'input')
            if first_out is not None:
                outs[first_out] = merged
        elif op.type in az._MATMUL:
            xs = [self.in_spec(n) for n in op.inputs.get('X', ())]
            wnames = op.inputs.get('Y', ()) or op.inputs.get('W', ())
            ws = [self.in_spec(n) for n in wnames]
            x = xs[0] if xs else None
            w = ws[0] if ws else None
            if x is not None and w is not None and len(x) >= 1 and \
                    len(w) >= 1 and x[-1] is not None and \
                    w[0] is not None and x[-1] != w[0]:
                self.on_mismatch(i, op, wnames[0], w,
                                 (x[-1],) + tuple(w[1:]), 'contraction')
            if first_out is not None:
                if x is not None and len(x) >= 1:
                    tail = (w[-1],) if w is not None and len(w) >= 1 \
                        else (None,)
                    outs[first_out] = tuple(x[:-1]) + tail
                elif w is not None:
                    outs[first_out] = None
        elif op.type in ('transpose', 'transpose2'):
            perm = op.attrs.get('axis') or op.attrs.get('perm')
            src = (op.inputs.get('X') or [None])[0]
            s = self.in_spec(src) if src else None
            if s is not None and perm and len(perm) == len(s) and \
                    first_out is not None:
                outs[first_out] = tuple(s[p] for p in perm)
        return outs

    def _finish(self, i, op, name, spec):
        declared = _declared(self.block, name)
        if declared is not None:
            if spec is not None and not _eqspec(spec, declared):
                self.on_mismatch(i, op, name, spec, declared, 'producer')
            spec = declared
        self.env[name] = spec


# --------------------------------------------------------- ZeRO planning
def _accumulators_of(block, op):
    """Persistable non-Param inputs of an optimizer update op whose shape
    matches the parameter's — the moment/velocity state ZeRO shards.
    Scalar state (beta-pow counters, LR) falls out via the shape test."""
    p = (op.inputs.get('Param') or [None])[0]
    pv = block._find_var_recursive(p) if p else None
    if pv is None or pv.shape is None:
        return p, pv, []
    accs = []
    for slot, names in op.inputs.items():
        if slot in ('Param', 'Grad', 'LearningRate'):
            continue
        for n in names:
            v = block._find_var_recursive(n)
            if v is not None and v is not pv and v.persistable and \
                    v.shape is not None and tuple(v.shape) == \
                    tuple(pv.shape):
                accs.append(v)
    return p, pv, accs


def plan_zero_specs(program, env=None):
    """{var name: canonical spec} of the ZeRO-sharded layout this pass
    would apply — parameters and their accumulators, each with the
    parameter's spec additionally split over the data axis on dim 0.

    Pure analysis of the (raw or optimized) program: memplan calls this
    to divide the per-device plan by the same math the rewriter applies,
    so the footprint table and the executed partitioning cannot drift.
    Returns ({name: spec}, {param: accumulator names}).
    """
    mesh_axes = program.mesh_axes()
    axis = zero_axis()
    if not zero_enabled() or not mesh_axes or axis not in mesh_axes:
        return {}, {}
    size = int(mesh_axes[axis])
    if size <= 1:
        return {}, {}
    block = program.global_block()
    ops = block.ops
    bw_idx = next((i for i, op in enumerate(ops)
                   if op.type == _BACKWARD_OP), None)
    specs, state = {}, {}
    for ui, op in enumerate(ops):
        if op.type not in _OPT_UPDATE_OPS:
            continue
        p, pv, accs = _accumulators_of(block, op)
        if pv is None or pv.shape is None or not pv.shape:
            continue
        base = _pad(env.get(p) if env else pv._sharding_spec,
                    len(pv.shape))
        if axis in spec_axes(base):
            continue  # already split over the data axis somewhere
        if base[0] is not None or int(pv.shape[0]) % size != 0:
            continue  # dim 0 taken or not evenly divisible
        zspec = (axis,) + tuple(base[1:])
        # parameter storage shards too, but ONLY when every post-backward
        # reader is this update op itself (the forward gets an explicit
        # all_gather; an unexpected reader would silently see the shard)
        shard_param = bw_idx is not None
        if shard_param:
            for oi, other in enumerate(ops):
                if oi <= bw_idx or other is op:
                    continue
                reads = set(other.input_names()) | \
                    set(other.attrs.get('params', ()))
                if p in reads or other.attrs.get('sub_block') is not None:
                    shard_param = False
                    break
        if shard_param:
            specs[p] = zspec
        for v in accs:
            specs[v.name] = zspec
        state[p] = [v.name for v in accs]
    return specs, state


# ----------------------------------------------------------- rewriting
def _mk_var(block, like, name, spec):
    v = block.create_var(name=name, dtype=like.dtype, shape=like.shape,
                         persistable=False)
    v.stop_gradient = getattr(like, 'stop_gradient', False)
    if spec is not None:
        v.sharding = spec
    return v


def _mk_collective(block, kind, src_name, dst_name, src_spec, dst_spec,
                   bytes_, extra=None):
    from ..framework import Operator
    attrs = {'src_spec': spec_to_jsonable(tuple(src_spec or ())),
             'dst_spec': spec_to_jsonable(tuple(dst_spec or ())),
             'bytes': int(bytes_)}
    attrs.update(extra or {})
    op = Operator(block, kind, inputs={'X': src_name},
                  outputs={'Out': dst_name}, attrs=attrs)
    return op


def _rewire_inputs(op, old, new):
    changed = False
    for slot, names in op.inputs.items():
        if old in names:
            op.inputs[slot] = [new if n == old else n for n in names]
            changed = True
    return changed


def _insert(block, idx, op):
    block.ops.insert(idx, op)
    for n in op.output_names():
        v = block._find_var_recursive(n)
        if v is not None:
            v.op = op


def _bytes_of(block, name, have, mesh_axes):
    return _analysis_rules()._var_bytes(block, name, have, mesh_axes)


def run(program, ctx):
    stats = {'specs_completed': 0, 'reshards_inserted': 0,
             'grad_allreduce': 0, 'all_gathers': 0, 'zero_params': 0,
             'zero_state_vars': 0, 'collectives_fused': 0,
             'collective_bytes': 0}
    mesh_axes = program.mesh_axes()
    if not enabled() or not mesh_axes:
        return stats
    block = program.global_block()

    def _complete():
        wrote = 0
        for name, spec in _Propagator(program).walk().items():
            if spec is None:
                continue
            v = block.vars.get(name)
            if v is None or v._sharding_spec is not None:
                continue
            if v.shape is not None and len(spec) > len(v.shape):
                continue  # rank overflow is the analyzer's D017, not ours
            v.sharding = spec
            wrote += 1
        stats['specs_completed'] += wrote
        return wrote

    # ---- complete: write propagated specs onto unannotated vars
    _complete()
    env = _Propagator(program).walk()
    persist = ctx.persistable

    ops = block.ops
    bw_idx = next((i for i, op in enumerate(ops)
                   if op.type == _BACKWARD_OP), None)

    # ---- zero: optimizer-state (and param-storage) sharding
    zspecs, zstate = plan_zero_specs(program, env)
    existing_gathers = {(op.inputs.get('X') or [None])[0]
                        for op in ops if op.type == 'all_gather'}
    gather_plan = []  # (first_use_idx, param, base_spec)
    for name, zspec in zspecs.items():
        v = block.vars.get(name)
        if v is None:
            continue
        is_param = isinstance(v, Parameter)
        base = _pad(env.get(name), len(v.shape or ()))
        if v._sharding_spec != zspec:
            v.sharding = zspec
        env[name] = zspec
        if is_param:
            stats['zero_params'] += 1
            if bw_idx is not None and name not in existing_gathers:
                first = next(
                    (i for i, op in enumerate(ops[:bw_idx])
                     if name in op.input_names()), None)
                if first is not None:
                    gather_plan.append((first, name, base))
        else:
            stats['zero_state_vars'] += 1
    # insert gathers back-to-front so earlier indices stay valid
    for first, name, base in sorted(gather_plan, reverse=True):
        v = block.vars[name]
        full = _mk_var(block, v, name + '@FULL', tuple(base))
        g = _mk_collective(block, 'all_gather', name, full.name,
                           zspecs[name], base,
                           _bytes_of(block, name, zspecs[name],
                                     mesh_axes))
        g.attrs['rng_stream'] = ops[first].attrs.get('rng_stream', first)
        for op in ops[first:bw_idx]:
            _rewire_inputs(op, name, full.name)
        _insert(block, first, g)
        env[full.name] = tuple(base)
        stats['all_gathers'] += 1
        stats['collective_bytes'] += g.attrs['bytes']
        bw_idx += 1

    # ---- grads: one explicit grad_allreduce per parameter
    if bw_idx is not None:
        bw_op = ops[bw_idx]
        pnames = list(bw_op.attrs.get('params', ()))
        gnames = list(bw_op.outputs.get('Grads', ()))
        reduced = {(op.inputs.get('X') or [None])[0]
                   for op in ops if op.type == 'grad_allreduce'}
        sub_reads = set()
        for b in program.blocks:
            if b.idx != 0:
                for op in b.ops:
                    sub_reads.update(op.input_names())
        insert_at = bw_idx + 1
        for p, g in zip(pnames, gnames):
            if g in reduced or g in sub_reads or g in persist:
                continue
            gv = block._find_var_recursive(g)
            if gv is None:
                continue
            dst = zspecs.get(p, env.get(p))
            src = env.get(g)
            ar = _mk_var(block, gv, g + '@AR', dst)
            extra = {'param': p}
            if zero_axis() in mesh_axes:
                extra['axis_name'] = zero_axis()
            arop = _mk_collective(block, 'grad_allreduce', g, ar.name,
                                  src, dst,
                                  _bytes_of(block, g, src, mesh_axes),
                                  extra)
            arop.attrs['rng_stream'] = bw_op.attrs.get('rng_stream',
                                                       bw_idx)
            for op in ops[insert_at:]:
                _rewire_inputs(op, g, ar.name)
            _insert(block, insert_at, arop)
            env[ar.name] = dst
            insert_at += 1
            stats['grad_allreduce'] += 1
            stats['collective_bytes'] += arop.attrs['bytes']

    # ---- reshard: materialize every remaining D018 edge
    edges = []

    def on_mismatch(i, op, name, have, want, kind):
        edges.append((i, op, name, tuple(have or ()), tuple(want or ()),
                      kind))

    _Propagator(program, on_mismatch).walk()
    # apply back-to-front so recorded indices stay valid
    n_rs = 0
    for i, op, name, have, want, kind in sorted(
            edges, key=lambda e: e[0], reverse=True):
        v = block._find_var_recursive(name)
        if v is None:
            continue
        if v.shape is not None and len(want) > len(v.shape):
            want = want[:len(v.shape)]  # trailing entries are replication
        if _eqspec(have, want):
            continue
        by = _bytes_of(block, name, have, mesh_axes)
        if kind == 'producer':
            # the producing op's dataflow layout disagrees with the
            # declared annotation: route the producer through a fresh
            # var and reshard into the annotated name
            if sum(1 for n in op.output_names() if n == name) != 1:
                continue
            src = _mk_var(block, v, name + '@SRC%d' % n_rs, have)
            for slot, names in op.outputs.items():
                if name in names:
                    op.outputs[slot] = [src.name if n == name else n
                                        for n in names]
            src.op = op
            rs = _mk_collective(block, 'reshard', src.name, name, have,
                                want, by)
            rs.attrs['rng_stream'] = op.attrs.get('rng_stream', i)
            _insert(block, i + 1, rs)
        else:
            # a consumer needs `name` in a different layout: reshard
            # into a fresh var read only by THIS op
            dst = _mk_var(block, v, name + '@RS%d' % n_rs, want)
            rs = _mk_collective(block, 'reshard', name, dst.name, have,
                                want, by)
            rs.attrs['rng_stream'] = op.attrs.get('rng_stream', i)
            _rewire_inputs(op, name, dst.name)
            _insert(block, i, rs)
        n_rs += 1
        stats['reshards_inserted'] += 1
        stats['collective_bytes'] += rs.attrs['bytes']

    # ---- fuse: collapse adjacent collectives on single-consumer edges
    readers = {}
    for op in block.ops:
        for n in op.input_names():
            readers.setdefault(n, []).append(op)
    i = 0
    while i < len(block.ops):
        a = block.ops[i]
        if a.type not in COLLECTIVE_OPS:
            i += 1
            continue
        out = (a.outputs.get('Out') or [None])[0]
        rs = readers.get(out, [])
        if out in persist or out in ctx.fetch_names or len(rs) != 1 or \
                rs[0].type not in COLLECTIVE_OPS:
            i += 1
            continue
        b = rs[0]
        # reduce-then-X and all-gather-then-reduce both keep the
        # reduction; pure layout chains stay a reshard
        kind = 'grad_allreduce' \
            if 'grad_allreduce' in (a.type, b.type) else \
            ('all_gather' if b.type == 'all_gather' else 'reshard')
        b.type = kind
        src_name = (a.inputs.get('X') or [None])[0]
        _rewire_inputs(b, out, src_name)
        b.attrs['src_spec'] = a.attrs.get('src_spec')
        b.attrs['bytes'] = int(a.attrs.get('bytes', 0))
        if a.attrs.get('param') and not b.attrs.get('param'):
            b.attrs['param'] = a.attrs['param']
        block.ops.pop(i)
        block.vars.pop(out, None)
        program._sharding.pop(out, None)
        readers = {}
        for op in block.ops:
            for n in op.input_names():
                readers.setdefault(n, []).append(op)
        stats['collectives_fused'] += 1
        program._bump()

    # ---- final sweep: the rewrites above unlock more inferences (grad
    # vars inherit ZeRO'd param specs); writing them now keeps the pass
    # idempotent — a second run finds nothing left to complete
    _complete()

    if stats['reshards_inserted'] or stats['grad_allreduce'] or \
            stats['all_gathers'] or stats['specs_completed']:
        program._bump()
    return stats
