"""paddle_tpu.core.passes — the Program->Program optimizing rewriter.

The reference framework rewrites ProgramDesc before execution
(paddle/fluid/framework/ir/ graph passes + the memory_optimize
transpiler); this package is the TPU-native analog, run by the executor
on the lowering-cache-miss path so the tracer sees fewer, larger ops
(Tensor Processing Primitives, arxiv 2104.05755; whole-program rewriting
ahead of XLA, arxiv 1810.09868).

Passes, in order (each ``run(program, ctx) -> stats`` mutates a private
clone in place):

  dce               dead-op/dead-var elimination (shared walker with the
                    analysis D005/D006 pass, kill-on-overwrite rule)
  const_fold        compile-time-constant chains -> one fill_constant,
                    evaluated through the op's own kernel (dtype-exact)
  cse               duplicate (type, inputs, attrs) ops rebind to one
  shard             GSPMD-style partitioner (mesh-declared programs
                    only): completes sharding specs, materializes D018
                    edges as explicit reshard/grad_allreduce/all_gather
                    collectives, ZeRO-shards optimizer state
  fuse_elementwise  consecutive elementwise/glue runs -> one
                    fused_elementwise op replaying the sub-program
  canon             64-bit attr narrowing + cross-block initializer dedup

Environment:
  PT_OPT=1 (default) enables the pipeline; PT_OPT=0 is the kill switch.
  PT_OPT_SKIP=pass,pass disables individual passes by name.
  PT_SHARD=1 (default) arms the shard pass (inert without a declared
  mesh); PT_SHARD_ZERO=1 arms its optimizer-state sharding tier.

Invariants: deterministic (same program -> same rewrite), idempotent
(optimizing an optimized program is a no-op), `source_loc` preserved on
surviving/folded/fused ops (fused ops carry their first sub-op's), and
bitwise-parity with the unfused lowering — RNG streams are pinned by
stamping every op's original trace position into an ``rng_stream`` attr
that ``registry.OpCtx.rng`` prefers over the live op index.
"""
import os
import time

from . import walker  # noqa: F401  (re-exported for analysis/)
from . import dce, const_fold, cse, fuse, canon, shard

__all__ = ['enabled', 'skip_set', 'config_token', 'optimize_program',
           'maybe_optimize', 'pass_names', 'PASSES', 'walker']

PASSES = (
    ('dce', dce.run),
    ('const_fold', const_fold.run),
    ('cse', cse.run),
    ('shard', shard.run),
    ('fuse_elementwise', fuse.run),
    ('canon', canon.run),
)


def pass_names():
    return [n for n, _ in PASSES]


def enabled():
    return os.environ.get('PT_OPT', '1') not in ('0', 'false', 'False')


def skip_set():
    raw = os.environ.get('PT_OPT_SKIP', '')
    return frozenset(p.strip() for p in raw.split(',') if p.strip())


def config_token():
    """Everything PT_OPT-shaped that changes what the tracer sees — part
    of the executor's hot cache key and the retrace explainer's launch
    signature, so toggling the pipeline mid-process reads as a named
    change instead of a mystery retrace."""
    if not enabled():
        return ('off',)
    return (('on',) + tuple(sorted(skip_set() & set(pass_names())))
            + shard.config_token())


class PassCtx(object):
    """Per-pass view of the program: the liveness roots plus the two
    name sets every pass guards on (recomputed between passes — each
    rewrite changes them)."""

    def __init__(self, program, fetch_names):
        self.program = program
        self.fetch_names = tuple(fetch_names)
        self.persistable = walker.persistable_names(program)
        self.cf_pinned = walker.control_flow_pinned(program)
        counts = {}
        for b in program.blocks:
            for op in b.ops:
                for n in op.output_names():
                    counts[n] = counts.get(n, 0) + 1
        self.multi_written = {n for n, c in counts.items() if c > 1}


def _op_count(program):
    return sum(len(b.ops) for b in program.blocks)


def _stamp_rng_streams(program):
    """Pin every op's RNG stream to its ORIGINAL trace position (the
    executor derives op streams from the live op index; rewrites shift
    indices).  setdefault keeps re-optimization idempotent.  Sub-blocks
    use the control_flow_exec offset convention (idx * 4096)."""
    for b in program.blocks:
        offset = 0 if b.idx == 0 else b.idx * 4096
        for i, op in enumerate(b.ops):
            op.attrs.setdefault('rng_stream', offset + i)


def _clone(program):
    p = program.clone(for_test=False)
    # clone() covers blocks/ops/random_seed; lowering also keys on these
    p._amp = getattr(program, '_amp', False)
    p._sharding = dict(getattr(program, '_sharding', {}))
    p._is_test = getattr(program, '_is_test', False)
    # clone() never rebuilds producer links, and control_flow_exec's
    # static-bound derivation walks var.op — restore them (last writer
    # wins, matching append_op)
    for b in p.blocks:
        for op in b.ops:
            for n in op.output_names():
                v = b._find_var_recursive(n)
                if v is not None:
                    v.op = op
    return p


def optimize_program(program, fetch_names=(), skip=None):
    """Run the pipeline on a CLONE of `program`; returns (program', stats).

    The input program is never mutated — the executor keys its caches on
    the raw program and hands the optimized twin to the tracer.
    """
    skip = skip_set() if skip is None else frozenset(skip)
    opt = _clone(program)
    # the executor's PT_LINT hook runs on the RAW program (user bugs must
    # not be DCE'd away before the gate); mark the twin so _lower skips
    # re-linting it
    opt._opt_of = True
    _stamp_rng_streams(opt)
    stats = {'op_count_raw': _op_count(program), 'passes': {},
             'pass_ms': 0.0}
    for name, fn in PASSES:
        if name in skip:
            continue
        t0 = time.perf_counter()
        pass_stats = fn(opt, PassCtx(opt, fetch_names)) or {}
        ms = (time.perf_counter() - t0) * 1000.0
        pass_stats['ms'] = round(ms, 3)
        stats['passes'][name] = pass_stats
        stats['pass_ms'] += ms
    stats['pass_ms'] = round(stats['pass_ms'], 3)
    stats['op_count_opt'] = _op_count(opt)
    stats['ops_removed'] = sum(
        p.get('ops_removed', 0) for p in stats['passes'].values())
    stats['ops_fused'] = stats['passes'].get(
        'fuse_elementwise', {}).get('ops_fused', 0)
    opt._bump()
    return opt, stats


_MEMO_MAX = 8


def maybe_optimize(program, fetch_names=()):
    """PT_OPT-gated, memoized entry used by the executor.  Returns
    (program', stats) — or (program, None) untouched when disabled."""
    if not enabled():
        return program, None
    token = config_token()
    key = (program._version, tuple(fetch_names), token)
    memo = getattr(program, '_opt_memo', None)
    if memo is None:
        memo = program._opt_memo = {}
    hit = memo.get(key)
    if hit is not None:
        return hit
    opt, stats = optimize_program(program, fetch_names)
    from ... import observability as _obs
    if _obs.enabled():
        shard_stats = stats['passes'].get('shard') or {}
        if shard_stats.get('reshards_inserted') or \
                shard_stats.get('grad_allreduce') or \
                shard_stats.get('all_gathers'):
            _obs.metrics.counter('opt.reshards_inserted').inc(
                shard_stats['reshards_inserted'])
            _obs.metrics.counter('opt.collective_bytes').inc(
                shard_stats.get('collective_bytes', 0))
        _obs.metrics.counter('opt.ops_removed').inc(stats['ops_removed'])
        _obs.metrics.counter('opt.ops_fused').inc(stats['ops_fused'])
        _obs.metrics.counter('opt.pass_ms').inc(stats['pass_ms'])
        _obs.metrics.counter('opt.runs').inc()
        _obs.instant('executor.optimize', cat='compile',
                     args={'raw': stats['op_count_raw'],
                           'opt': stats['op_count_opt'],
                           'pass_ms': stats['pass_ms']})
    while len(memo) >= _MEMO_MAX:
        memo.pop(next(iter(memo)))
    memo[key] = (opt, stats)
    return opt, stats
