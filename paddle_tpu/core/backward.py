"""Autodiff on the Program.

Capability parity with reference python/paddle/fluid/backward.py
(append_backward, calc_gradient) — redesigned TPU-first: instead of inserting
one hand-written grad OpDesc per forward op (the reference keeps ~400 grad
kernels in paddle/fluid/operators/*_grad), we insert a single `__backward__`
op that the Executor lowers with `jax.vjp` over the traced forward prefix.
XLA's autodiff-generated HLO is fused with the forward pass in one
executable — no per-op grad kernel launches, and every op automatically has a
correct gradient.

Grad variables keep the reference naming convention `<var>@GRAD` and are real
Variables in the block: regularizers, gradient clipping and optimizer ops
appended afterwards operate on them exactly like in the reference.
"""
from . import framework
from .framework import Variable, OpRole

__all__ = ['append_backward', 'gradients', 'calc_gradient']

GRAD_SUFFIX = '@GRAD'


def _grad_name(name):
    return name + GRAD_SUFFIX


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append a backward pass for `loss`; returns [(param, grad), ...].

    Reference: backward.py append_backward (same signature / return value).
    """
    assert isinstance(loss, Variable), 'loss must be a Variable'
    block = loss.block
    program = block.program
    root = program.global_block()

    if parameter_list is not None:
        params = []
        for p in parameter_list:
            name = p if isinstance(p, str) else p.name
            params.append(root.var(name))
    else:
        params = [p for p in root.all_parameters() if p.trainable]
    no_grad = set()
    for n in (no_grad_set or []):
        no_grad.add(n.name if isinstance(n, Variable) else n)
    params = [p for p in params if p.name not in no_grad]
    if not params:
        raise ValueError('append_backward: no trainable parameters found')

    with framework.op_role_guard(OpRole.Backward):
        grad_vars = []
        for p in params:
            g = root.create_var(name=_grad_name(p.name), shape=p.shape,
                                dtype=p.dtype, persistable=False,
                                stop_gradient=True)
            grad_vars.append(g)
        loss_grad = root.create_var(name=_grad_name(loss.name),
                                    shape=loss.shape, dtype=loss.dtype,
                                    stop_gradient=True)
        block.append_op(
            type='__backward__',
            inputs={'Loss': loss},
            outputs={'Grads': grad_vars, 'LossGrad': loss_grad},
            attrs={'params': [p.name for p in params]},
            infer_shape=False)
    return [(p, root.var(_grad_name(p.name))) for p in params]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Compute grads of targets wrt arbitrary inputs (reference
    backward.gradients / calc_gradient)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, 'gradients(): single target supported'
    loss = targets[0]
    block = loss.block
    with framework.op_role_guard(OpRole.Backward):
        grad_vars = []
        for x in inputs:
            g = block.create_var(name=_grad_name(x.name), shape=x.shape,
                                 dtype=x.dtype, stop_gradient=True)
            grad_vars.append(g)
        block.append_op(
            type='__backward__',
            inputs={'Loss': loss},
            outputs={'Grads': grad_vars},
            attrs={'params': [x.name for x in inputs], 'wrt_vars': True},
            infer_shape=False)
    return grad_vars


calc_gradient = gradients
