"""Compilation persistence: fingerprints, the bounded executable LRU, and
the on-disk AOT cache that warm-starts fresh processes.

The Julia->TPU compile-the-loop model (arxiv 1810.09868) treats the whole
program as one ahead-of-time compilation artifact.  This module gives
paddle_tpu the same property: every lowered executable is addressed by a
**canonical fingerprint** — a stable hash over the serialized ProgramDesc,
the launch signature (feed shapes/dtypes, fetch set, steps=K, mesh layout,
param specs, AMP policy, check_nan) and the environment (jax/jaxlib
version, backend platform + chip kind) — and stored in two tiers:

  L1  in-process map, LRU-bounded by ``PT_EXEC_CACHE_MAX`` (default 64).
      Evictions count into the ``pt_exec_cache_evictions`` metric; the
      seed executor grew this map without limit across programs.
  L2  on-disk store under ``PT_CACHE_DIR`` (default ``~/.cache/paddle_tpu``)
      holding executables serialized through JAX's AOT path
      (``jit(fn).lower(...).compile()`` + ``serialize_executable``).  A
      backend that cannot serialize executables falls back to caching the
      lowered StableHLO text — inspectable, and the XLA-level persistent
      cache (``jax_compilation_cache_dir``, wired below as the backstop)
      still shortcuts the backend compile on the retrace.

Corrupt, truncated, or version-mismatched disk entries are MISSES, never
errors: the entry is deleted and the caller recompiles.  Disable the disk
tier with ``PT_CACHE=0`` (the test suite does — cache-hit timing would
make retrace-count assertions order-dependent).
"""
import hashlib
import json
import os
import pickle
import threading
from collections import OrderedDict

from .. import observability as _obs
from ..testing import faults as _faults
from .retry import retry_with_backoff

__all__ = ['launch_fingerprint', 'callable_fingerprint',
           'program_fingerprint', 'ExecutableLRU', 'DiskCache', 'disk_cache',
           'cache_dir', 'disk_enabled', 'ensure_xla_cache_backstop']

# bump when the on-disk payload layout changes: old entries become misses
CACHE_FORMAT = 1

_DEFAULT_DIR = os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu')


def disk_enabled():
    return os.environ.get('PT_CACHE', '1') not in ('0', 'false', 'False')


def cache_dir():
    return os.environ.get('PT_CACHE_DIR', _DEFAULT_DIR)


# ------------------------------------------------------------ fingerprints

def program_fingerprint(program):
    """Stable hash of the serialized ProgramDesc (+ AMP flag and sharding
    annotations, which change the lowering without touching the desc).
    Cached on the program keyed by its mutation counter, so the desc walk
    runs once per edit, not once per launch."""
    cached = getattr(program, '_pt_fingerprint', None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    from .. import io as fluid_io
    desc = fluid_io.program_to_desc(program)
    desc['_amp'] = bool(getattr(program, '_amp', False))
    desc['_sharding'] = {n: str(s) for n, s in
                        sorted(getattr(program, '_sharding', {}).items())}
    blob = json.dumps(desc, sort_keys=True, default=str)
    fp = hashlib.sha256(blob.encode()).hexdigest()
    program._pt_fingerprint = (program._version, fp)
    return fp


def _environment_blob():
    """Everything outside the program that decides executable validity."""
    import jax
    import jaxlib
    try:
        dev0 = jax.devices()[0]
        backend = (dev0.platform, str(dev0.device_kind), jax.device_count())
    except Exception:  # noqa: BLE001 - no backend yet: still fingerprintable
        backend = ('none', 'none', 0)
    return {
        'format': CACHE_FORMAT,
        'jax': jax.__version__,
        'jaxlib': jaxlib.__version__,
        'backend': backend,
        'x64': bool(jax.config.jax_enable_x64),
        'amp_flow': os.environ.get('PT_AMP_FLOW', 'conv'),
    }


def _mesh_blob(mesh):
    if mesh is None:
        return None
    return {'axes': [str(a) for a in mesh.axis_names],
            'shape': list(mesh.devices.shape)}


def launch_fingerprint(program, feed_specs, fetch_names, steps, check_nan,
                       mesh=None, param_specs=None, extra=None):
    """The canonical cache key: program + launch signature + environment.

    feed_specs / param_specs: {name: (shape_tuple, dtype_str)}.  Param
    specs come from the scope at lowering time — an executable compiled
    for f32 params can never be handed bf16 ones (the AOT artifact has no
    re-specialization path, unlike jit)."""
    blob = {
        'program': program_fingerprint(program),
        'feeds': {n: [list(s), d] for n, (s, d) in sorted(feed_specs.items())},
        'params': {n: [list(s), d] for n, (s, d) in
                   sorted((param_specs or {}).items())},
        'fetch': list(fetch_names),
        'steps': steps,
        'check_nan': bool(check_nan),
        'mesh': _mesh_blob(mesh),
        'env': _environment_blob(),
        'extra': extra,
    }
    canon = json.dumps(blob, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()


def callable_fingerprint(kind, spec, param_specs=None):
    """Cache key for AOT executables that are NOT program launches — the
    streaming decode loop, prefill chunks, and similar hand-built jitted
    callables.  ``kind`` namespaces the producer; ``spec`` is any
    JSON-able blob that pins the callable's structure (model config,
    cache geometry, window size, mesh layout); ``param_specs`` follows
    the launch_fingerprint convention {name: (shape_tuple, dtype_str)}."""
    blob = {
        'kind': str(kind),
        'spec': spec,
        'params': {n: [list(s), d] for n, (s, d) in
                   sorted((param_specs or {}).items())},
        'env': _environment_blob(),
    }
    canon = json.dumps(blob, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()


# ------------------------------------------------------------ in-process L1

class ExecutableLRU(object):
    """Bounded insertion/access-ordered map for compiled-executable entries.

    The seed executor's ``self._cache`` dict grew one entry per
    (program, feeds, fetches, K, scope) forever; long-running services
    compiling many programs leaked every executable they ever built.
    Capacity comes from ``PT_EXEC_CACHE_MAX`` (default 64); each eviction
    increments ``pt_exec_cache_evictions``."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(os.environ.get('PT_EXEC_CACHE_MAX', '64'))
        self.capacity = max(1, int(capacity))
        self._map = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            entry = self._map.get(key)
            if entry is not None:
                self._map.move_to_end(key)
            return entry

    def put(self, key, entry):
        with self._lock:
            self._map[key] = entry
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
                _obs.metrics.counter('pt_exec_cache_evictions').inc()

    def __len__(self):
        return len(self._map)

    def __contains__(self, key):
        return key in self._map

    def clear(self):
        with self._lock:
            self._map.clear()


# ------------------------------------------------------------ on-disk L2

class DiskCache(object):
    """Content-addressed executable store: ``<dir>/v<FMT>/<fp[:2]>/<fp>.pkl``.

    Payloads are pickled dicts carrying either a serialized executable
    (``tier='exec'``: the (bytes, in_tree, out_tree) triple from
    ``serialize_executable.serialize``) or the lowered StableHLO text
    (``tier='stablehlo'``).  Every load failure — unpickleable, truncated,
    foreign format, deserialize error — deletes the entry and reports a
    miss."""

    def __init__(self, root=None):
        self._root = root

    @property
    def root(self):
        return self._root if self._root is not None else cache_dir()

    def _path(self, fingerprint):
        return os.path.join(self.root, 'v%d' % CACHE_FORMAT,
                            fingerprint[:2], fingerprint + '.pkl')

    def load(self, fingerprint):
        """Returns (compiled_or_None, tier_or_None).  ``('…', 'exec')`` is
        a full hit (trace AND compile skipped); ``(None, 'stablehlo')``
        means only the HLO was cached — the caller retraces, with the XLA
        backstop shortcutting the backend compile; ``(None, None)`` is a
        miss."""
        path = self._path(fingerprint)

        def _read():
            _faults.maybe_fail('cache_read')
            with open(path, 'rb') as f:
                return pickle.load(f)

        try:
            # transient OSErrors (a racing writer's os.replace mid-flight
            # on a shared PT_CACHE_DIR, NFS hiccups, injected cache_read
            # faults) retry with backoff; a missing entry is an ordinary
            # miss and never retries
            payload = retry_with_backoff(_read, retry_on=(OSError,),
                                         give_up_on=(FileNotFoundError,),
                                         name='cache_read')
        except FileNotFoundError:
            return None, None
        except Exception:  # noqa: BLE001 - corruption is a miss
            self._drop(path, 'unreadable')
            return None, None
        try:
            if (payload.get('format') != CACHE_FORMAT or
                    payload.get('fingerprint') != fingerprint):
                raise ValueError('format/fingerprint mismatch')
            if payload['tier'] == 'exec':
                from jax.experimental import serialize_executable as se
                serialized, in_tree, out_tree = payload['payload']
                compiled = se.deserialize_and_load(serialized, in_tree,
                                                   out_tree)
                _obs.metrics.counter('compile_cache.bytes_read').inc(
                    os.path.getsize(path))
                return compiled, 'exec'
            if payload['tier'] == 'stablehlo':
                return None, 'stablehlo'
            raise ValueError('unknown tier %r' % (payload.get('tier'),))
        except Exception:  # noqa: BLE001 - stale entries die quietly
            self._drop(path, 'undeserializable')
            return None, None

    def store(self, fingerprint, compiled=None, lowered=None, meta=None):
        """Serialize ``compiled`` (preferred) or fall back to the lowered
        StableHLO.  Returns the tier written, or None when nothing could
        be persisted.  Failures never propagate: persistence is an
        optimization, not a correctness dependency."""
        payload = None
        if compiled is not None:
            try:
                from jax.experimental import serialize_executable as se
                payload = {'tier': 'exec', 'payload': se.serialize(compiled)}
            except Exception:  # noqa: BLE001 - backend can't serialize
                payload = None
        if payload is None and lowered is not None:
            try:
                payload = {'tier': 'stablehlo', 'payload': lowered.as_text()}
            except Exception:  # noqa: BLE001
                return None
        if payload is None:
            return None
        payload['format'] = CACHE_FORMAT
        payload['fingerprint'] = fingerprint
        payload['meta'] = dict(meta or {}, env=_environment_blob())
        path = self._path(fingerprint)
        tmp = path + '.tmp.%d' % os.getpid()

        def _write():
            _faults.maybe_fail('cache_write')
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, 'wb') as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)  # atomic: concurrent readers never see torn

        try:
            # transient write errors (injected cache_write faults, brief
            # volume pressure) retry with backoff before giving up
            retry_with_backoff(_write, retry_on=(OSError,),
                               name='cache_write')
            _obs.metrics.counter('compile_cache.disk_stores').inc()
            _obs.metrics.counter('compile_cache.bytes_written').inc(
                os.path.getsize(path))
        except Exception:  # noqa: BLE001 - read-only/full disk: skip caching
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return payload['tier']

    @staticmethod
    def _drop(path, reason):
        _obs.metrics.counter('compile_cache.corrupt_entries').inc()
        try:
            os.unlink(path)
        except OSError:
            pass


_DISK = DiskCache()


def disk_cache():
    return _DISK


# ------------------------------------------------------- XLA-level backstop

_XLA_WIRED = [False]


def ensure_xla_cache_backstop():
    """Point jax's persistent compilation cache at ``$PT_CACHE_DIR/xla``.

    This is the third tier: when only StableHLO could be cached (or a jit
    fallback retraces), the retrace still happens in Python but XLA's
    backend compile — the dominant cost — is served from disk.  A user
    who already configured ``jax_compilation_cache_dir`` wins; we never
    override."""
    if _XLA_WIRED[0] or not disk_enabled():
        return
    _XLA_WIRED[0] = True
    import jax
    try:
        if jax.config.jax_compilation_cache_dir:
            return
        jax.config.update('jax_compilation_cache_dir',
                          os.path.join(cache_dir(), 'xla'))
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          float(os.environ.get('PT_CACHE_XLA_MIN_S', '0')))
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
    except Exception:  # noqa: BLE001 - older jaxlib without these knobs
        pass
