"""Shared transient-failure retry: bounded exponential backoff.

Disk I/O on shared infrastructure fails transiently — two processes
racing on one ``PT_CACHE_DIR``, NFS hiccups, a checkpoint volume briefly
remounting.  Treating every such error as fatal turned BENCH-grade soaks
into dead rounds; swallowing them silently hides real corruption.  This
module gives every disk-touching subsystem (core/compile_cache.py, io.py,
train/checkpoint.py) one policy: retry with deterministic exponential
backoff, count every attempt in observability, and re-raise the last
error once the budget is spent.
"""
import os
import time

from .. import observability as _obs

__all__ = ['retry_with_backoff']


def retry_with_backoff(fn, attempts=None, base_delay=0.02, max_delay=0.5,
                       retry_on=(OSError,), give_up_on=(), name=None,
                       sleep=time.sleep):
    """Call ``fn()`` up to ``attempts`` times (default ``PT_RETRIES``+1,
    env default 2 retries).

    ``retry_on`` exceptions are retried after ``base_delay * 2**i``
    seconds (capped at ``max_delay``, deterministic — no jitter, so
    failure-path tests replay exactly); ``give_up_on`` exceptions
    propagate immediately even when they subclass a retryable type
    (``FileNotFoundError`` under ``OSError`` is the canonical case: a
    missing cache entry is a miss, not a transient fault).  Each retry
    counts into ``retry.attempts`` (and ``retry.attempts.<name>``); an
    exhausted budget counts ``retry.giveups`` and re-raises."""
    if attempts is None:
        attempts = 1 + max(0, int(os.environ.get('PT_RETRIES', '2')))
    attempts = max(1, int(attempts))
    for i in range(attempts):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if i + 1 >= attempts:
                _obs.metrics.counter('retry.giveups').inc()
                if name:
                    _obs.metrics.counter('retry.giveups.%s' % name).inc()
                raise
            _obs.metrics.counter('retry.attempts').inc()
            if name:
                _obs.metrics.counter('retry.attempts.%s' % name).inc()
            _obs.tracing.instant('retry.backoff', cat='fault',
                                 args={'name': name or '?', 'attempt': i + 1,
                                       'error': repr(e)[:200]})
            sleep(min(max_delay, base_delay * (2 ** i)))
