"""Shared transient-failure retry: bounded exponential backoff.

Disk I/O on shared infrastructure fails transiently — two processes
racing on one ``PT_CACHE_DIR``, NFS hiccups, a checkpoint volume briefly
remounting.  Treating every such error as fatal turned BENCH-grade soaks
into dead rounds; swallowing them silently hides real corruption.  This
module gives every disk-touching subsystem (core/compile_cache.py, io.py,
train/checkpoint.py) one policy: retry with deterministic exponential
backoff, count every attempt in observability, and re-raise the last
error once the budget is spent.
"""
import os
import random
import time
import zlib

from .. import observability as _obs

__all__ = ['retry_with_backoff']


def retry_with_backoff(fn, attempts=None, base_delay=0.02, max_delay=0.5,
                       retry_on=(OSError,), give_up_on=(), name=None,
                       sleep=time.sleep, jitter=None, seed=None):
    """Call ``fn()`` up to ``attempts`` times (default ``PT_RETRIES``+1,
    env default 2 retries).

    ``retry_on`` exceptions are retried after ``base_delay * 2**i``
    seconds (capped at ``max_delay``); ``give_up_on`` exceptions
    propagate immediately even when they subclass a retryable type
    (``FileNotFoundError`` under ``OSError`` is the canonical case: a
    missing cache entry is a miss, not a transient fault).  Each retry
    counts into ``retry.attempts`` (and ``retry.attempts.<name>``); an
    exhausted budget counts ``retry.giveups`` and re-raises.

    ``jitter`` (default ``PT_RETRY_JITTER``, env default 0) spreads each
    delay by up to ±``jitter`` fraction so N serving workers retrying a
    shared resource (one compile-cache entry, one checkpoint volume)
    don't retry in lockstep and re-collide forever.  The jitter is
    SEEDED, not wall-clock: ``seed`` (default: a crc32 of ``name`` mixed
    with the pid, so distinct workers de-sync while one process replays
    exactly) drives a private ``random.Random`` — the same seed yields
    the same backoff sequence every run, so failure-path tests stay as
    reproducible as the no-jitter default."""
    if attempts is None:
        attempts = 1 + max(0, int(os.environ.get('PT_RETRIES', '2')))
    attempts = max(1, int(attempts))
    if jitter is None:
        jitter = float(os.environ.get('PT_RETRY_JITTER', '0') or 0.0)
    rng = None
    if jitter:
        if seed is None:
            seed = zlib.crc32(
                ('%s:%d' % (name or '', os.getpid())).encode('utf-8'))
        rng = random.Random(seed)
    for i in range(attempts):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if i + 1 >= attempts:
                _obs.metrics.counter('retry.giveups').inc()
                if name:
                    _obs.metrics.counter('retry.giveups.%s' % name).inc()
                raise
            _obs.metrics.counter('retry.attempts').inc()
            if name:
                _obs.metrics.counter('retry.attempts.%s' % name).inc()
            _obs.tracing.instant('retry.backoff', cat='fault',
                                 args={'name': name or '?', 'attempt': i + 1,
                                       'error': repr(e)[:200]})
            delay = min(max_delay, base_delay * (2 ** i))
            if rng is not None:
                delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
            sleep(delay)
