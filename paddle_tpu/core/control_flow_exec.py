"""Graph-mode control flow lowering: While / conditional_block / tensor arrays.

Parity: reference paddle/fluid/operators/while_op.cc,
conditional_block_op.cc, lod_tensor_array ops
(python/paddle/fluid/layers/control_flow.py:504 `class While`).

TPU-native design.  The reference interprets a while op by re-running the
sub-block's op list on the CPU each iteration, with LoDTensorArrays as
growable vector<LoDTensor>.  Under whole-block XLA lowering the loop must be
a structured HLO loop:

* `while` lowers to a **masked `lax.scan`** when the trip-count upper bound
  is statically derivable from the condition chain (``less_than(i, n)`` with
  ``n`` a build-time constant): every iteration runs, and a carried
  ``active`` flag select-masks the writes.  This form is
  reverse-differentiable (training RNN-style loops works) and gives XLA a
  static trip count to schedule.
* Otherwise it lowers to `lax.while_loop` (forward-only: XLA/JAX cannot
  reverse-differentiate an unbounded loop).
* `conditional_block` lowers to `lax.cond` over the carried writes.

Loop **carries** are the vars written anywhere in the sub-block (including
nested sub-blocks) that already exist in the enclosing environment — the
same def-use rule the reference's while_op uses to decide which parent-scope
vars the body mutates.

Tensor arrays are carried as a ``TensorArrayVal`` pytree: a fixed-capacity
stacked buffer plus a dynamic length.  Capacity = the loop bound (or the
explicit ``create_array(capacity=)``).  Element shape/dtype are discovered
by a **speculative body trace** on the pre-loop values; the speculative
outputs are discarded, so XLA dead-code-eliminates the extra trace and only
the zero-initialised buffer survives.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .dtypes import jax_dtype

# ops this module executes natively (no registry impl, no shape inference)
NATIVE_OPS = {'while', 'conditional_block', 'write_to_array',
              'read_from_array', 'array_length', 'recurrent'}

# while loops with a static bound at or under this lower to a masked scan
# (differentiable); larger/unknown bounds use lax.while_loop (forward-only)
_SCAN_BOUND_LIMIT = 16384


@jax.tree_util.register_pytree_node_class
class TensorArrayVal(object):
    """Runtime tensor array: fixed-capacity buffer [cap, *elem] + length."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    def tree_flatten(self):
        return (self.buffer, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class _Unallocated(object):
    """Placeholder for an array before its first write fixes elem shape."""

    def __init__(self, capacity):
        self.capacity = capacity


# capacity hint pushed by the enclosing While lowering (loop bound)
_cap_hint = [None]


def _scalar_index(i):
    i = jnp.asarray(i)
    if i.ndim > 0:
        i = i.reshape(-1)[0]
    return i.astype(jnp.int32)


def _written_names(block, program):
    """All var names written by the block's ops, descending into nested
    sub-blocks (their writes to outer vars are still writes)."""
    names = []
    for op in block.ops:
        names.extend(op.output_names())
        sb = op.attrs.get('sub_block')
        if sb is not None:
            names.extend(_written_names(program.block(sb), program))
    # preserve order, drop dups
    seen = set()
    out = []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out


def _static_bound(cond_name, block):
    """Derive a static trip-count upper bound from the condition producer:
    ``less_than(i, n)`` where n is a build-time fill_constant."""
    cvar = block._find_var_recursive(cond_name)
    if cvar is None or cvar.op is None:
        return None
    op = cvar.op
    if op.type != 'less_than':
        return None
    yvar = block._find_var_recursive(op.inputs['Y'][0])
    if yvar is None or yvar.op is None or yvar.op.type != 'fill_constant':
        return None
    try:
        return int(yvar.op.attrs['value'])
    except (TypeError, ValueError):
        return None


def _run_block(sub, env, ectx, program):
    from . import executor as _exec
    _exec._exec_ops(sub.ops, sub.idx * 4096, env, ectx, program)


def _coerce_carry(new, old, name):
    """Carried var after one body pass must keep its aval: cast dtype back
    (paddle vars have a fixed dtype; jnp promotion inside the body must not
    leak), and hard-error on shape drift."""
    if isinstance(old, TensorArrayVal) or isinstance(new, TensorArrayVal):
        return new
    new = jnp.asarray(new)
    old = jnp.asarray(old)
    if new.shape != old.shape:
        raise ValueError(
            'while-loop carry "%s" changed shape %s -> %s inside the body; '
            'loop-carried vars must keep a fixed shape under XLA'
            % (name, old.shape, new.shape))
    if new.dtype != old.dtype:
        new = new.astype(old.dtype)
    return new


def _prealloc_arrays(sub, env, ectx, program, carry_names, bound):
    """Speculatively trace the body once on the pre-loop env to discover the
    element shape of any tensor array first written inside the loop, then
    replace it in `env` with a zeroed buffer.  The speculative values are
    discarded -> XLA DCE removes the duplicate trace."""
    arr_names = [n for n in carry_names
                 if isinstance(env.get(n), (_Unallocated, type(None)))
                 and _is_array_var(sub, n)]
    if not arr_names:
        return
    spec_env = dict(env)
    old_hint = _cap_hint[0]
    _cap_hint[0] = bound
    try:
        _run_block(sub, spec_env, ectx, program)
    finally:
        _cap_hint[0] = old_hint
    for n in arr_names:
        v = spec_env.get(n)
        if not isinstance(v, TensorArrayVal):
            raise ValueError(
                'tensor array "%s" is carried by a while loop but the body '
                'never writes it with a resolvable element shape' % n)
        env[n] = TensorArrayVal(jnp.zeros_like(v.buffer),
                                jnp.asarray(0, jnp.int32))


def _is_array_var(block, name):
    v = block._find_var_recursive(name)
    return v is not None and getattr(v, 'is_tensor_array', False)


def exec_control_flow_op(op, env, ectx, op_index, program):
    if op.type == 'while':
        _exec_while(op, env, ectx, program)
    elif op.type == 'recurrent':
        _exec_recurrent(op, env, ectx, program)
    elif op.type == 'conditional_block':
        _exec_cond_block(op, env, ectx, program)
    elif op.type == 'write_to_array':
        _exec_array_write(op, env)
    elif op.type == 'read_from_array':
        _exec_array_read(op, env)
    elif op.type == 'array_length':
        arr = _get_array(env, op.inputs['A'][0])
        env[op.outputs['Out'][0]] = arr.length.reshape((1,)).astype(jax_dtype('int64'))
    else:
        raise KeyError('unknown native control-flow op %s' % op.type)


# --------------------------------------------------------------- arrays

def _get_array(env, name):
    v = env.get(name)
    if not isinstance(v, TensorArrayVal):
        raise ValueError(
            'tensor array "%s" read before any write; initialize it with '
            'array_write first' % name)
    return v


def _exec_array_write(op, env):
    name = op.outputs['Out'][0]
    x = jnp.asarray(env[op.inputs['X'][0]])
    i = _scalar_index(env[op.inputs['I'][0]])
    cur = env.get(name)
    if not isinstance(cur, TensorArrayVal):
        cap = cur.capacity if isinstance(cur, _Unallocated) else None
        cap = cap or _cap_hint[0] or op.attrs.get('capacity')
        if cap is None:
            raise ValueError(
                'cannot size tensor array "%s": no static loop bound was '
                'derivable and no explicit capacity given — use '
                'create_array(dtype, capacity=N)' % name)
        cur = TensorArrayVal(jnp.zeros((int(cap),) + x.shape, x.dtype),
                             jnp.asarray(0, jnp.int32))
    buf = lax.dynamic_update_index_in_dim(cur.buffer, x.astype(
        cur.buffer.dtype), i, 0)
    length = jnp.maximum(cur.length, i + 1)
    env[name] = TensorArrayVal(buf, length)


def _exec_array_read(op, env):
    arr = _get_array(env, op.inputs['A'][0])
    i = _scalar_index(env[op.inputs['I'][0]])
    env[op.outputs['Out'][0]] = lax.dynamic_index_in_dim(
        arr.buffer, i, 0, keepdims=False)


# ---------------------------------------------------------------- while

def _exec_while(op, env, ectx, program):
    sub = program.block(op.attrs['sub_block'])
    cond_name = op.inputs['Condition'][0]
    written = _written_names(sub, program)
    if cond_name not in written:
        raise ValueError(
            'While body never updates its condition var "%s" — the loop '
            'would not terminate. Update it with layers.less_than(..., '
            'cond=cond) or layers.assign.' % cond_name)
    bound = _static_bound(cond_name, sub)

    # tensor arrays written in the body need a pre-sized buffer carry
    carry_names = [n for n in written if n in env or _is_array_var(sub, n)]
    _prealloc_arrays(sub, env, ectx, program, carry_names, bound)
    carry_names = [n for n in carry_names if n in env]
    if cond_name not in carry_names:
        carry_names.append(cond_name)

    init = {n: env[n] for n in carry_names}

    def body(carry):
        env2 = dict(env)
        env2.update(carry)
        _run_block(sub, env2, ectx, program)
        return {n: _coerce_carry(env2[n], carry[n], n) for n in carry_names}

    def cond_of(carry):
        c = jnp.asarray(carry[cond_name])
        return jnp.all(c) if c.ndim else c

    if bound is not None and bound <= _SCAN_BOUND_LIMIT:
        # masked scan: fixed trip count, reverse-differentiable
        def step(carry, _):
            active = cond_of(carry)
            new = body(carry)
            merged = jax.tree_util.tree_map(
                lambda a, b: jnp.where(active, a, b), new, carry)
            return merged, None
        final, _ = lax.scan(step, init, None, length=int(bound))
    else:
        final = lax.while_loop(cond_of, body, init)
    env.update(final)


# ----------------------------------------------------------- recurrent

def _exec_recurrent(op, env, ectx, program):
    """Lower a `recurrent` op (StaticRNN / DynamicRNN step block) to ONE
    `lax.scan` over the time axis.

    Parity: reference paddle/fluid/operators/recurrent_op.cc, which
    re-interprets the step block per time step on the host with memory
    linkage.  Here the step block is traced once and scanned — XLA sees a
    single fused loop body, reverse-differentiable for training.

    attrs:
      sub_block     step-body block index
      step_vars     step-local per-step input var names   [len = n_seq]
      seq_vars      their source sequence var names
      mem_vars      step-local pre-memory var names       [len = n_mem]
      init_vars     their initial-value var names
      update_vars   var whose post-step value is the next memory
      out_vars      step-local output var names
      stack_vars    parent-level stacked output var names
      time_major    True: seqs are [T, B, ...] (StaticRNN);
                    False: [B, T, ...] padded (DynamicRNN)
      length_var    optional [B] int lengths: steps at-or-past a row's
                    length freeze its memories and zero its outputs
    """
    sub = program.block(op.attrs['sub_block'])
    a = op.attrs
    time_major = a.get('time_major', True)
    seqs = [jnp.asarray(env[n]) for n in a['seq_vars']]
    if not seqs:
        raise ValueError('recurrent op needs at least one step_input')
    xs = [s if time_major else jnp.moveaxis(s, 1, 0) for s in seqs]
    T = int(xs[0].shape[0])
    inits = [jnp.asarray(env[n]) for n in a['init_vars']]
    lengths = None
    if a.get('length_var'):
        lengths = jnp.asarray(env[a['length_var']]).reshape(-1)

    step_vars, mem_vars = a['step_vars'], a['mem_vars']
    update_vars, out_vars = a['update_vars'], a['out_vars']

    def step(carry, t_and_x):
        t, xts = t_and_x
        env2 = dict(env)
        for name, val in zip(step_vars, xts):
            env2[name] = val
        for name, val in zip(mem_vars, carry):
            env2[name] = val
        _run_block(sub, env2, ectx, program)
        new = [_coerce_carry(env2[u], m, u)
               for u, m in zip(update_vars, carry)]
        outs = [jnp.asarray(env2[o]) for o in out_vars]
        if lengths is not None:
            active = t < lengths                       # [B]
            def msk(val, old):
                m = active.reshape((-1,) + (1,) * (val.ndim - 1))
                return jnp.where(m, val, old)
            new = [msk(nv, m) for nv, m in zip(new, carry)]
            outs = [msk(o, jnp.zeros_like(o)) for o in outs]
        return new, outs

    _, stacked = lax.scan(step, inits, (jnp.arange(T), xs))
    for name, val in zip(a['stack_vars'], stacked):
        env[name] = val if time_major else jnp.moveaxis(val, 0, 1)


# --------------------------------------------------------- conditional

def _exec_cond_block(op, env, ectx, program):
    sub = program.block(op.attrs['sub_block'])
    cond = jnp.asarray(env[op.inputs['Condition'][0]])
    pred = jnp.all(cond) if cond.ndim else cond

    written = _written_names(sub, program)
    carry_names = [n for n in written if n in env or _is_array_var(sub, n)]
    _prealloc_arrays(sub, env, ectx, program, carry_names, None)
    carry_names = [n for n in carry_names if n in env]
    operand = {n: env[n] for n in carry_names}

    def true_fn(carry):
        env2 = dict(env)
        env2.update(carry)
        _run_block(sub, env2, ectx, program)
        return {n: _coerce_carry(env2[n], carry[n], n) for n in carry_names}

    def false_fn(carry):
        return carry

    env.update(lax.cond(pred, true_fn, false_fn, operand))
