"""Graph IR: Program / Block / Operator / Variable.

Capability parity with reference python/paddle/fluid/framework.py
(Program, Block, Operator, Variable, program_guard, name_scope) — redesigned
TPU-first: the IR is pure Python (no protobuf/C++ desc), and a Block is not
interpreted op-by-op like the reference's C++ Executor; it is lowered in one
piece to a single XLA computation by tracing the registered JAX impl of every
op (see core/executor.py).  Shape inference runs `jax.eval_shape` on the op
impls at graph-construction time with two trial batch sizes, so batch dims
stay symbolic (-1) while feature dims are static — exactly what XLA needs.
"""
import contextlib
import copy
import numpy as np

from . import unique_name
from .dtypes import convert_dtype, dtype_str
from . import registry

__all__ = [
    'Program', 'Block', 'Operator', 'Variable', 'Parameter', 'program_guard',
    'default_main_program', 'default_startup_program', 'switch_main_program',
    'switch_startup_program', 'name_scope', 'cpu_places', 'cuda_places',
    'CPUPlace', 'CUDAPlace', 'TPUPlace', 'is_compiled_with_cuda',
    'get_flags', 'set_flags',
]

# Imperative (dygraph) mode: slot holds the active _ImperativeState while
# inside imperative.guard(); Block.append_op then executes ops eagerly.
_imperative = [None]

# ---------------------------------------------------------------- places

class Place(object):
    """Device spec. On TPU-native builds every place lowers to the same XLA
    backend; the class is kept for API parity with the reference's
    CPUPlace/CUDAPlace (paddle/fluid/platform/place.h)."""

    kind = 'tpu'

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id


class TPUPlace(Place):
    kind = 'tpu'


class CPUPlace(Place):
    kind = 'cpu'


class CUDAPlace(Place):
    # kept for source compatibility; maps to the default accelerator
    kind = 'tpu'


class CUDAPinnedPlace(Place):
    kind = 'cpu'


def cpu_places(device_count=None):
    return [CPUPlace(0)]


def cuda_places(device_ids=None):
    import jax
    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


def tpu_places(device_ids=None):
    return cuda_places(device_ids)


def is_compiled_with_cuda():
    return False


_flags = {}


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _flags.get(k) for k in keys}


def set_flags(d):
    _flags.update(d)


# ---------------------------------------------------------------- op role

class OpRole(object):
    Forward = 'forward'
    Backward = 'backward'
    Optimize = 'optimize'
    LRSched = 'lr_sched'
    Loss = 'loss'
    RPC = 'rpc'
    Dist = 'dist'


_current_role = [OpRole.Forward]


@contextlib.contextmanager
def op_role_guard(role):
    _current_role.append(role)
    try:
        yield
    finally:
        _current_role.pop()


# recompute (rematerialization) scopes: ops appended inside carry a
# recompute_id attr; the executor wraps each contiguous tagged run in
# jax.checkpoint, trading recompute FLOPs for activation memory
_recompute_stack = []
_recompute_counter = [0]


@contextlib.contextmanager
def recompute_scope(name=None):
    """Mark ops built inside for rematerialization (TPU-native replacement
    for the reference's memory_optimize transpiler, SURVEY §2.1): their
    activations are not saved for backward — they recompute in the vjp."""
    _recompute_counter[0] += 1
    rid = name or 'remat_%d' % _recompute_counter[0]
    _recompute_stack.append(rid)
    try:
        yield
    finally:
        _recompute_stack.pop()


_name_scope_stack = ['']


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(_name_scope_stack[-1] + (prefix or '') + '/')
    try:
        yield
    finally:
        _name_scope_stack.pop()


# ---------------------------------------------------------------- Variable

class Variable(object):
    """A named tensor in a Block.

    Parity: reference framework.py Variable / VarDesc. `shape` uses -1 for
    the batch dimension.  `lod_level > 0` marks a ragged sequence variable;
    TPU-native representation is dense padded data plus a companion
    `<name>@LENGTH` int32 vector (see core/lod.py), never a CPU-side LoD.
    """

    def __init__(self,
                 block,
                 name=None,
                 shape=None,
                 dtype='float32',
                 lod_level=0,
                 persistable=False,
                 stop_gradient=False,
                 is_data=False,
                 need_check_feed=False,
                 type=None,
                 initializer=None,
                 **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate('_generated_var')
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self._dtype = dtype_str(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self._persistable = persistable
        self._stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type or 'lod_tensor'
        self._sharding_spec = None  # canonical tuple spec (core/sharding.py)
        self.op = None  # producer op
        self._ivalue = None      # imperative mode: concrete jax.Array
        self._grad_value = None  # imperative mode: last computed gradient

    # ------- imperative (dygraph) API: value/grad access on eager vars -----
    def numpy(self):
        if self._ivalue is None:
            raise ValueError('var %s holds no eager value (imperative mode '
                             'only)' % self.name)
        return np.asarray(self._ivalue)

    _numpy = numpy

    def backward(self):
        from ..imperative import base as _imp_base
        _imp_base.eager_backward(self)

    _backward = backward

    def gradient(self):
        if self._grad_value is None:
            raise ValueError('var %s has no gradient (call backward first)'
                             % self.name)
        return np.asarray(self._grad_value)

    _gradient = gradient

    def clear_gradient(self):
        self._grad_value = None

    _clear_gradient = clear_gradient

    # ------- mutation-tracked attributes --------------------------------
    # In-place edits on an existing var (shape refinement, persistable
    # flips, sharding annotations) must invalidate the executor lowering
    # cache and the lint memo — both key on Program._version — so every
    # setter bumps.  Construction writes the underscore storage directly.

    def _bump_program(self):
        blk = getattr(self, 'block', None)
        if blk is not None:
            blk.program._bump()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, s):
        self._shape = tuple(s) if s is not None else None
        self._bump_program()

    @property
    def persistable(self):
        return self._persistable

    @persistable.setter
    def persistable(self, p):
        self._persistable = p
        self._bump_program()

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, s):
        self._stop_gradient = s
        self._bump_program()

    @property
    def sharding(self):
        """Canonical sharding spec (tuple per core/sharding.py) or None.
        Setting syncs Program._sharding (the executor's in_shardings
        source) with the PartitionSpec view and bumps the version."""
        return self._sharding_spec

    @sharding.setter
    def sharding(self, spec):
        from .sharding import normalize_spec, to_partition_spec
        spec = normalize_spec(spec)
        self._sharding_spec = spec
        blk = getattr(self, 'block', None)
        if blk is not None:
            prog = blk.program
            if spec is None:
                prog._sharding.pop(self.name, None)
            else:
                prog._sharding[self.name] = to_partition_spec(spec)
            prog._bump()

    @property
    def dtype(self):
        return self._dtype

    @dtype.setter
    def dtype(self, v):
        self._dtype = dtype_str(v)
        self._bump_program()

    @property
    def np_dtype(self):
        return convert_dtype(self._dtype)

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def to_string(self, throw_on_error=False, with_details=False):
        return "var %s : shape=%s dtype=%s lod=%d%s" % (
            self.name, self.shape, self._dtype, self.lod_level,
            ' persistable' if self.persistable else '')

    __repr__ = __str__ = lambda self: self.to_string()

    # -------- math op patch (reference layers/math_op_patch.py) --------
    def _cur_block(self):
        # ops emit into the program's CURRENT block, not the var's home
        # block — an expression on a root var inside a While body must
        # land in the loop body, or it reads the pre-loop value forever
        return self.block.program.current_block()

    def _binary(self, other, op_type, reverse=False):
        block = self._cur_block()
        if isinstance(other, Variable):
            x, y = (other, self) if reverse else (self, other)
            out = block.create_var(dtype=self._dtype)
            block.append_op(type=op_type,
                           inputs={'X': x, 'Y': y},
                           outputs={'Out': out},
                           attrs={'axis': -1})
            return out
        # scalar path
        v = float(other)
        if op_type == 'elementwise_add':
            return self._scale(1.0, v)
        if op_type == 'elementwise_sub':
            if reverse:
                return self._scale(-1.0, v)
            return self._scale(1.0, -v)
        if op_type == 'elementwise_mul':
            return self._scale(v, 0.0)
        # div / pow / mod etc: materialize a constant
        out = block.create_var(dtype=self._dtype)
        const = block.create_var(dtype=self._dtype)
        block.append_op(type='fill_constant',
                       inputs={}, outputs={'Out': const},
                       attrs={'shape': [1], 'value': v, 'dtype': self._dtype})
        x, y = (const, self) if reverse else (self, const)
        block.append_op(type=op_type, inputs={'X': x, 'Y': y},
                       outputs={'Out': out}, attrs={'axis': -1})
        return out

    def _scale(self, scale, bias):
        blk = self._cur_block()
        out = blk.create_var(dtype=self._dtype)
        blk.append_op(type='scale', inputs={'X': self},
                            outputs={'Out': out},
                            attrs={'scale': float(scale), 'bias': float(bias),
                                   'bias_after_scale': True})
        return out

    def __add__(self, o):
        return self._binary(o, 'elementwise_add')

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, 'elementwise_sub')

    def __rsub__(self, o):
        return self._binary(o, 'elementwise_sub', reverse=True)

    def __mul__(self, o):
        return self._binary(o, 'elementwise_mul')

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binary(o, 'elementwise_div')

    __truediv__ = __div__

    def __rdiv__(self, o):
        return self._binary(o, 'elementwise_div', reverse=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        return self._binary(o, 'elementwise_pow')

    def __rpow__(self, o):
        return self._binary(o, 'elementwise_pow', reverse=True)

    def __neg__(self):
        return self._scale(-1.0, 0.0)

    def _cmp(self, other, op_type):
        blk = self._cur_block()
        out = blk.create_var(dtype='bool')
        other = other if isinstance(other, Variable) else _const_like(self, other)
        blk.append_op(type=op_type, inputs={'X': self, 'Y': other},
                            outputs={'Out': out}, attrs={})
        return out

    def __lt__(self, o):
        return self._cmp(o, 'less_than')

    def __le__(self, o):
        return self._cmp(o, 'less_equal')

    def __gt__(self, o):
        return self._cmp(o, 'greater_than')

    def __ge__(self, o):
        return self._cmp(o, 'greater_equal')

    def astype(self, dtype):
        blk = self._cur_block()
        out = blk.create_var(dtype=dtype)
        blk.append_op(type='cast', inputs={'X': self},
                            outputs={'Out': out},
                            attrs={'in_dtype': self._dtype,
                                   'out_dtype': dtype_str(dtype)})
        return out


def _const_like(var, value):
    const = var.block.create_var(dtype=var.dtype)
    var.block.append_op(type='fill_constant', inputs={}, outputs={'Out': const},
                       attrs={'shape': [1], 'value': float(value),
                              'dtype': var.dtype})
    return const


class Parameter(Variable):
    """Trainable persistable variable (reference framework.py Parameter)."""

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop('trainable', True)
        self.optimize_attr = kwargs.pop('optimize_attr', {'learning_rate': 1.0})
        self.regularizer = kwargs.pop('regularizer', None)
        self.gradient_clip_attr = kwargs.pop('gradient_clip_attr', None)
        self.do_model_average = kwargs.pop('do_model_average', None)
        self.is_distributed = kwargs.pop('is_distributed', False)
        super(Parameter, self).__init__(
            block, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=False, **kwargs)


# ---------------------------------------------------------------- Operator

# Source-location capture: each Operator remembers the (file, line) of the
# model code that created it, so lint diagnostics (paddle_tpu.analysis)
# point at the user's line instead of deep framework internals.  Frames
# inside the package are skipped, EXCEPT the bundled model zoo — a finding
# in paddle_tpu/models should name the model line.  PT_SOURCE_LOC=0
# disables the walk entirely (it is a few frame hops per op).
import os as _os
import sys as _sys

_PKG_DIR = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_MODELS_DIR = _os.path.join(_PKG_DIR, 'models')
_CAPTURE_SOURCE_LOC = _os.environ.get('PT_SOURCE_LOC', '1') not in (
    '0', 'false', 'False')


def _capture_source_loc():
    if not _CAPTURE_SOURCE_LOC:
        return None
    try:
        f = _sys._getframe(2)
    except ValueError:
        return None
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) or fn.startswith(_MODELS_DIR):
            return (fn, f.f_lineno)
        f = f.f_back
        depth += 1
    return None


class _AttrDict(dict):
    """Operator.attrs wrapper: in-place mutation bumps the owning
    program's version so the lowering cache and the lint memo (both
    keyed on Program._version) never serve stale results.  No-op writes
    (setdefault on a present key, re-setting an identical value) do NOT
    bump, keeping versions stable across idempotent rewriter passes."""

    __slots__ = ('_op',)

    def __init__(self, data, op):
        super(_AttrDict, self).__init__(data)
        self._op = op

    def _bump(self):
        blk = getattr(self._op, 'block', None) if self._op is not None \
            else None
        if blk is not None:
            blk.program._bump()

    @staticmethod
    def _same(a, b):
        try:
            return bool(a == b)
        except Exception:       # ndarray-valued attrs and other oddballs
            return False

    def __setitem__(self, k, v):
        if k in self and self._same(dict.__getitem__(self, k), v):
            return
        dict.__setitem__(self, k, v)
        self._bump()

    def __delitem__(self, k):
        dict.__delitem__(self, k)
        self._bump()

    def setdefault(self, k, default=None):
        if k in self:
            return dict.__getitem__(self, k)
        self[k] = default
        return default

    def update(self, *a, **kw):
        for k, v in dict(*a, **kw).items():
            self[k] = v

    def pop(self, k, *default):
        had = k in self
        out = dict.pop(self, k, *default)
        if had:
            self._bump()
        return out

    def popitem(self):
        out = dict.popitem(self)
        self._bump()
        return out

    def clear(self):
        if self:
            dict.clear(self)
            self._bump()

    # deepcopy / pickle must NOT drag the op (and through it the whole
    # program) along — clone() deep-copies attrs and re-wraps on assign
    def __deepcopy__(self, memo):
        return {copy.deepcopy(k, memo): copy.deepcopy(v, memo)
                for k, v in self.items()}

    def __reduce__(self):
        return (dict, (dict(self),))


class Operator(object):
    """One node in a Block: op type + named input/output slots + attrs.

    Parity: reference framework.py Operator / OpDesc.  Unlike the reference,
    there is no per-op kernel: `type` keys into core/registry.py for a JAX
    impl used both for build-time shape inference and whole-block lowering.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.source_loc = _capture_source_loc()
        self.attrs = dict(attrs or {})
        self.attrs.setdefault('op_role', _current_role[-1])
        if _recompute_stack:
            self.attrs.setdefault('recompute_id', _recompute_stack[-1])
        self.inputs = {}        # slot -> list[str]
        self.outputs = {}       # slot -> list[str]
        self.input_is_list = {}
        self.output_is_list = {}
        for slot, vs in (inputs or {}).items():
            if vs is None:
                continue
            self.input_is_list[slot] = isinstance(vs, (list, tuple))
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            self.inputs[slot] = [v.name if isinstance(v, Variable) else v
                                 for v in vs]
        for slot, vs in (outputs or {}).items():
            if vs is None:
                continue
            self.output_is_list[slot] = isinstance(vs, (list, tuple))
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            self.outputs[slot] = [v.name if isinstance(v, Variable) else v
                                  for v in vs]

    @property
    def attrs(self):
        return self._attrs

    @attrs.setter
    def attrs(self, d):
        if isinstance(d, _AttrDict) and d._op is self:
            self._attrs = d
        else:
            self._attrs = _AttrDict(dict(d or {}), self)
        blk = getattr(self, 'block', None)
        if blk is not None:
            blk.program._bump()

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def attr(self, name):
        return self.attrs.get(name)

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump()

    set_attr = _set_attr

    def has_attr(self, name):
        return name in self.attrs

    def to_string(self, *a, **k):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        hidden = {'op_role'}
        ats = {k: v for k, v in self.attrs.items() if k not in hidden}
        return "{%s} = %s(%s) %s" % (outs, self.type, ins, ats)

    __repr__ = __str__ = lambda self: self.to_string()


# ---------------------------------------------------------------- Block

_INFER_B1, _INFER_B2 = 7, 11


class Block(object):
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    @property
    def parent(self):
        return (self.program.blocks[self.parent_idx]
                if self.parent_idx >= 0 else None)

    # ------------- vars -------------
    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("var %s not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        return None

    def create_var(self, name=None, **kwargs):
        if name is None:
            name = unique_name.generate('_generated_var')
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name=name, **kwargs)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name=None, shape=None, dtype='float32', **kw):
        if name is None:
            name = unique_name.generate('_param')
        if _imperative[0] is not None:
            # eager mode: a same-named initialized parameter is reused, so a
            # Layer's repeated forward calls share weights across iterations
            existing = self.program.blocks[0].vars.get(name)
            if isinstance(existing, Parameter) and \
                    existing._ivalue is not None:
                return existing
        # parameters always live in the global (root) block, like the ref
        # (and their .block must BE the root block — optimizer passes
        # append update ops to param.block, which must never be a
        # control-flow sub-block)
        root = self.program.blocks[0]
        p = Parameter(root, shape=shape, dtype=dtype, name=name, **kw)
        root.vars[name] = p
        self.program._bump()
        return p

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def iter_parameters(self):
        return iter(self.all_parameters())

    # ------------- ops -------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump()
        for n in op.output_names():
            ov = self._find_var_recursive(n)
            if ov is not None:
                ov.op = op
        if _imperative[0] is not None:
            from ..imperative import base as _imp_base
            _imp_base.eager_run_op(op)
        elif infer_shape and registry.has_op(type):
            self._infer_shapes(op)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None,
                   infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        if infer_shape and registry.has_op(type):
            self._infer_shapes(op)
        return op

    def _infer_shapes(self, op):
        """Dual-batch abstract eval: run the op's JAX impl under
        jax.eval_shape with batch placeholder 7 and again with 11; output
        dims that differ between the two runs are batch dims (-1)."""
        import jax

        impl = registry.get_op(op.type).impl
        results = []
        for B in (_INFER_B1, _INFER_B2):
            ins = {}
            ok = True
            for slot, names in op.inputs.items():
                structs = []
                for n in names:
                    v = self._find_var_recursive(n)
                    if v is None or v.shape is None:
                        ok = False
                        break
                    shape = tuple(B if d in (-1, None) else int(d)
                                  for d in v.shape)
                    structs.append(
                        jax.ShapeDtypeStruct(shape, v.np_dtype))
                if not ok:
                    break
                ins[slot] = structs if op.input_is_list[slot] else structs[0]
            if not ok:
                return  # cannot infer (e.g. shapeless input); leave as-is
            ctx = registry.InferCtx(op)
            try:
                out = jax.eval_shape(lambda kw: impl(ctx, kw, op.attrs), ins)
            except Exception as e:
                raise RuntimeError(
                    "shape inference failed for op %s: %s\n%s" %
                    (op.type, e, op.to_string()))
            results.append(out)
        r1, r2 = results
        for slot, names in op.outputs.items():
            o1 = r1.get(slot) if isinstance(r1, dict) else None
            o2 = r2.get(slot) if isinstance(r2, dict) else None
            if o1 is None:
                continue
            l1 = o1 if isinstance(o1, (list, tuple)) else [o1]
            l2 = o2 if isinstance(o2, (list, tuple)) else [o2]
            for n, s1, s2 in zip(names, l1, l2):
                v = self._find_var_recursive(n)
                if v is None:
                    continue
                shape = tuple(int(a) if a == b else -1
                              for a, b in zip(s1.shape, s2.shape))
                v.shape = shape
                v.dtype = s1.dtype

    def to_string(self, throw_on_error=False, with_details=False):
        lines = ["block %d:" % self.idx]
        for v in self.vars.values():
            lines.append("  " + v.to_string())
        for op in self.ops:
            lines.append("  " + op.to_string())
        return "\n".join(lines)

    __repr__ = __str__ = lambda self: self.to_string()


# ---------------------------------------------------------------- Program

class Program(object):
    """An ordered collection of Blocks — the full training/inference graph.

    Parity: reference framework.py Program / ProgramDesc.  `_version` is a
    mutation counter used by the Executor's lowering cache (the reference
    recompiles its SSA graph on desc change; we re-trace/re-jit)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._seed_counter = 0
        self._is_test = False
        # sharding annotations attached by parallel/transpiler.py
        self._sharding = {}
        # declared device mesh (tuple of (axis_name, size) pairs), HBM
        # budget in bytes, and serving KV-pool plan (CacheConfig kwargs)
        # — inputs to the sharding/memplan lint passes (analysis/passes)
        self._mesh_axes = None
        self._device_limit_bytes = None
        self._kv_plan = None
        # bf16 auto-mixed-precision for MXU ops (set_amp / contrib amp)
        self._amp = False

    def _bump(self):
        self._version += 1

    def set_amp(self, flag=True):
        """Enable bf16 auto-mixed-precision: matmul-class ops run with
        bfloat16 inputs (MXU native), everything else stays float32.  The
        lowered executable re-jits on change."""
        self._amp = bool(flag)
        self._bump()

    def set_sharding(self, name, spec):
        """Attach a PartitionSpec to var `name`; bumps the version so the
        executor's lowering cache re-jits with the new in_shardings.
        When the var exists in the IR the spec also becomes a
        first-class `Variable.sharding` annotation (canonical tuple
        form, serialized by io.py); unknown names keep the legacy
        side-table-only behavior."""
        for b in self.blocks:
            v = b.vars.get(name)
            if v is not None:
                v.sharding = spec  # setter syncs self._sharding + bumps
                return
        self._sharding[name] = spec
        self._bump()

    def set_mesh_axes(self, axes):
        """Declare the device mesh the sharding specs refer to.  Accepts
        a name->size dict, a sequence of (name, size) pairs, a jax Mesh
        (axis_names/shape), or None to clear.  The D019 lint checks spec
        axes against this declaration."""
        if axes is None:
            self._mesh_axes = None
        elif hasattr(axes, 'axis_names'):  # jax.sharding.Mesh
            self._mesh_axes = tuple((str(a), int(axes.shape[a]))
                                    for a in axes.axis_names)
        elif isinstance(axes, dict):
            self._mesh_axes = tuple((str(k), int(v))
                                    for k, v in axes.items())
        else:
            self._mesh_axes = tuple((str(k), int(v)) for k, v in axes)
        self._bump()

    def mesh_axes(self):
        """Declared mesh as a name->size dict, or None."""
        return dict(self._mesh_axes) if self._mesh_axes is not None else None

    def set_device_limit(self, limit_bytes):
        """Declare the per-device HBM budget the memplan lint (D020)
        checks against; None clears it (the pass then queries the
        runtime's memory_stats when available)."""
        self._device_limit_bytes = (int(limit_bytes)
                                    if limit_bytes is not None else None)
        self._bump()

    def set_kv_plan(self, **cache_config_kwargs):
        """Declare the serving KV-cache pool this program runs against
        (serving.generation.CacheConfig kwargs); the memplan lint folds
        its pool bytes into the per-device footprint.  No kwargs clears
        the plan."""
        self._kv_plan = dict(cache_config_kwargs) or None
        self._bump()

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx
        if self.current_block_idx < 0:
            self.current_block_idx = 0

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    def all_parameters(self):
        return self.global_block().all_parameters()

    @property
    def num_blocks(self):
        return len(self.blocks)

    def clone(self, for_test=False):
        """Deep-copy the program.  for_test=True keeps only forward ops,
        flips is_test attrs on (dropout/batch_norm/...) ops, like the ref."""
        p = Program()
        p.random_seed = self.random_seed
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(nb, shape=v.shape, dtype=v.dtype, name=name,
                                   trainable=v.trainable,
                                   optimize_attr=v.optimize_attr,
                                   regularizer=v.regularizer,
                                   gradient_clip_attr=v.gradient_clip_attr)
                else:
                    nv = Variable(nb, name=name, shape=v.shape, dtype=v.dtype,
                                  lod_level=v.lod_level,
                                  persistable=v.persistable,
                                  stop_gradient=v.stop_gradient,
                                  is_data=v.is_data, type=v.type)
                # side-channel markers the lowering reads via getattr:
                # tensor-array vars (control_flow_exec) and ragged-length
                # companions (sequence layers)
                if getattr(v, 'is_tensor_array', False):
                    nv.is_tensor_array = True
                if getattr(v, 'lod_length_name', None):
                    nv.lod_length_name = v.lod_length_name
                if v._sharding_spec is not None:
                    nv._sharding_spec = v._sharding_spec
                nb.vars[name] = nv
            for op in b.ops:
                role = op.attrs.get('op_role', OpRole.Forward)
                if for_test and role in (OpRole.Backward, OpRole.Optimize,
                                         OpRole.LRSched):
                    continue
                nattrs = copy.deepcopy(op.attrs)
                if for_test and 'is_test' in nattrs:
                    nattrs['is_test'] = True
                nop = Operator(nb, op.type)
                nop.attrs = nattrs
                nop.source_loc = op.source_loc
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nop.input_is_list = dict(op.input_is_list)
                nop.output_is_list = dict(op.output_is_list)
                nb.ops.append(nop)
            p.blocks.append(nb)
        p._sharding = dict(self._sharding)
        p._mesh_axes = self._mesh_axes
        p._device_limit_bytes = self._device_limit_bytes
        p._kv_plan = dict(self._kv_plan) if self._kv_plan else None
        if for_test:
            p._is_test = True
        p._bump()
        return p

    def _prune(self, feeds, fetches):
        """Return a clone keeping only ops needed to compute `fetches` from
        `feeds` (reference Program._prune_with_input, used by
        save_inference_model)."""
        feed_names = set(v.name if isinstance(v, Variable) else v
                        for v in feeds)
        fetch_names = set(v.name if isinstance(v, Variable) else v
                          for v in fetches)
        p = self.clone(for_test=True)
        b = p.global_block()
        needed = set(fetch_names)
        kept = []
        for op in reversed(b.ops):
            if set(op.output_names()) & needed:
                kept.append(op)
                for n in op.input_names():
                    if n not in feed_names:
                        needed.add(n)
        b.ops = list(reversed(kept))
        used = set(feed_names) | set(fetch_names)
        for op in b.ops:
            used.update(op.input_names())
            used.update(op.output_names())
        b.vars = {n: v for n, v in b.vars.items() if n in used}
        p._bump()
        return p

    def lint(self, feed_names=(), fetch_list=(), bucketer=None,
             passes=None, optimize=False):
        """Static analysis without compiling: run the paddle_tpu.analysis
        passes (def-use, shape/dtype abstract interpretation, dead ops,
        donation conflicts, retrace hazards, numerical hazards) and
        return a LintResult.  Never raises — strict enforcement is the
        executor's PT_LINT policy (docs/analysis.md).

        fetch_list anchors the dead-op pass; bucketer (a
        data_feeder.FeedBucketer) tells the retrace pass which dynamic
        feed dims are already padded onto stable bucket signatures.

        optimize=True first runs the PT_OPT rewriter pipeline
        (core/passes, honoring PT_OPT_SKIP) and lints the OPTIMIZED
        program — what the executor actually traces under PT_OPT=1.
        Diagnostics still point at model `source_loc` (folded/fused ops
        inherit their originals').  Default False so findings the
        rewriter would fix (dead ops, 64-bit attrs) stay visible when
        linting the program as written.
        """
        from ..analysis import lint_program
        fetch_names = []
        for f in (fetch_list or ()):
            fetch_names.append(f.name if isinstance(f, Variable) else f)
        target = self
        if optimize:
            from .passes import optimize_program
            target, _ = optimize_program(self, tuple(fetch_names))
        return lint_program(target, feed_names=tuple(feed_names),
                            fetch_names=tuple(fetch_names),
                            bucketer=bucketer, passes=passes)

    def to_string(self, throw_on_error=False, with_details=False):
        return "\n".join(b.to_string() for b in self.blocks)

    __repr__ = __str__ = lambda self: self.to_string()


# ------------------------------------------------- default program stack

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_start = None
    if startup_program is not None:
        old_start = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_start is not None:
            switch_startup_program(old_start)
