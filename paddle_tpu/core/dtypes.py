"""Dtype normalization between fluid-style strings and numpy/jax dtypes.

Parity: reference paddle/fluid/framework/data_type.{h,cc} VarType mapping.
"""
import numpy as np

_STR2NP = {
    'float32': np.float32,
    'float64': np.float64,
    'float16': np.float16,
    'bfloat16': None,  # filled lazily from ml_dtypes via jax.numpy
    'int64': np.int64,
    'int32': np.int32,
    'int16': np.int16,
    'int8': np.int8,
    'uint8': np.uint8,
    'bool': np.bool_,
}


def _bf16():
    import jax.numpy as jnp
    return jnp.bfloat16


def convert_dtype(dtype):
    """Normalize a dtype spec (str | np.dtype | jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == 'bfloat16':
            return np.dtype(_bf16())
        if dtype not in _STR2NP:
            raise ValueError("unsupported dtype string: %s" % dtype)
        return np.dtype(_STR2NP[dtype])
    return np.dtype(dtype)


_X64_NARROW = {'int64': 'int32', 'uint64': 'uint32',
               'float64': 'float32', 'complex128': 'complex64'}


def jax_dtype(dtype):
    """convert_dtype for values materialized INSIDE a jax computation.

    With x64 disabled (the default), asking jnp.full/astype for a 64-bit
    dtype emits a warn-and-truncate per trace; the truncation is the
    semantics we run with either way, so map 64->32 bit explicitly here
    and keep the traces silent.  Host-side numpy arrays (feeds, readers)
    keep full convert_dtype widths."""
    d = convert_dtype(dtype)
    if d.name in _X64_NARROW:
        import jax
        if not jax.config.jax_enable_x64:
            return np.dtype(_X64_NARROW[d.name])
    return d


def dtype_str(dtype):
    d = convert_dtype(dtype)
    name = d.name
    return name


def is_float(dtype):
    return convert_dtype(dtype).kind == 'f' or dtype_str(dtype) == 'bfloat16'
