"""Dtype normalization between fluid-style strings and numpy/jax dtypes.

Parity: reference paddle/fluid/framework/data_type.{h,cc} VarType mapping.
"""
import numpy as np

_STR2NP = {
    'float32': np.float32,
    'float64': np.float64,
    'float16': np.float16,
    'bfloat16': None,  # filled lazily from ml_dtypes via jax.numpy
    'int64': np.int64,
    'int32': np.int32,
    'int16': np.int16,
    'int8': np.int8,
    'uint8': np.uint8,
    'bool': np.bool_,
}


def _bf16():
    import jax.numpy as jnp
    return jnp.bfloat16


def convert_dtype(dtype):
    """Normalize a dtype spec (str | np.dtype | jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == 'bfloat16':
            return np.dtype(_bf16())
        if dtype not in _STR2NP:
            raise ValueError("unsupported dtype string: %s" % dtype)
        return np.dtype(_STR2NP[dtype])
    return np.dtype(dtype)


def dtype_str(dtype):
    d = convert_dtype(dtype)
    name = d.name
    return name


def is_float(dtype):
    return convert_dtype(dtype).kind == 'f' or dtype_str(dtype) == 'bfloat16'
