"""EmitEngine: memoized per-signature op lowering (see package docstring).

The engine is built once per ``_resolve_entry`` miss, on the OPTIMIZED
program twin (post core/passes — emission must see the same
``fused_elementwise``/``rng_stream`` shape the tracer would).  Its three
jobs:

1. **Static coverage walk** at construction: every op in every block
   must be emit-capable or the whole program falls back to traced
   lowering (EmitFallback — per-program, loud, strict-gateable).
2. **Demanded-output analysis**: a per-op-instance mask of which output
   slots anything downstream can observe (readers anywhere, writeback,
   fetches, the loss, the slim vjp keep-set).  Undemanded outputs are
   pruned from the memoized function's return — this is what restores
   bitwise parity with the traced path, where jax's global DCE removes
   dead chains that a naively-memoized op boundary would pin alive
   (a dead ``log_softmax`` auxiliary output, left as a vjp primal,
   otherwise splits the jvp and changes float association).  Ops with
   NO demanded outputs are skipped entirely — except effectful ops
   ('print'), which always dispatch.
3. **Per-op dispatch** (``run_op``, called from the executor's
   ``_exec_ops_plain`` under the outer trace): canonicalize the op to a
   signature key, build-or-reuse the jitted pure function, apply it.
   RNG fold-in stream bases travel as traced arguments so ops differing
   only in ``rng_stream`` share one signature bitwise.

The memo is PROCESS-WIDE, not per-engine: the second lowering of the
same workload (run_steps after run, a ParallelExecutor twin) hits every
memoized function, and stable function identity keeps jax's own pjit
trace cache warm underneath.
"""
import time

import numpy as np

from . import EMITTER_VERSION, EmitError, EmitFallback
from .. import registry
from ..control_flow_exec import NATIVE_OPS as _CONTROL_FLOW
from ..passes.cse import RNG_OPS as _RNG_BASE

# hand raw-lax rules self-register against the op registry on import
from . import rules as _rules  # noqa: F401,E402

__all__ = ['EmitEngine', 'unsupported_ops', 'op_capability', 'clear_memo']

# ops whose kernels may draw from ctx.rng (core/passes/cse.py owns the
# base set — the CSE pass must refuse to merge these for the same
# reason the emitter must thread streams to them); sample_tokens is the
# serving-path addition that postdates that list
RNG_OPS = set(_RNG_BASE) | {'sample_tokens'}

# effectful kernels (host side effects under jax.debug.*): never skipped
# by dead-output pruning — the effect IS the point
EFFECTFUL_OPS = {'print'}

# static deny-list: op types the emitter must not attempt (empty today;
# tests monkeypatch it to exercise the fallback path, and a future op
# whose kernel resists memoized emission gets parked here loudly
# instead of producing wrong numbers)
DENY_OPS = set()

# executor-native op types handled outside the registry dispatch
_NATIVE = {'__backward__'} | set(_CONTROL_FLOW)


def op_capability(op_type):
    """(capable, why) — the single capability test shared by the engine's
    coverage walk and the pt_lint D015 pass."""
    if op_type in _NATIVE:
        return True, 'executor-native'
    if op_type in DENY_OPS:
        return False, 'deny-listed for direct emission'
    if not registry.has_op(op_type):
        return False, 'no registered kernel'
    return True, 'kernel' if registry.get_op(op_type).emit is None \
        else 'rule'


def unsupported_ops(program):
    """[(op_type, why)] across all blocks, deduped by type."""
    out, seen = [], set()
    for block in program.blocks:
        for op in block.ops:
            if op.type in seen:
                continue
            seen.add(op.type)
            ok, why = op_capability(op.type)
            if not ok:
                out.append((op.type, why))
    return out


# ------------------------------------------------------ canonical keys
_SKIP_ATTRS = {'op_role', 'rng_stream', 'recompute_id'}


def _canonv(v):
    if isinstance(v, dict):
        return tuple(sorted((k, _canonv(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_canonv(x) for x in v)
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return v
    return repr(v)


def _canon_attrs(op_type, attrs):
    """Attrs with identity-irrelevant keys dropped; fused sub-programs
    alpha-renamed (var names -> positional ids) so e.g. every layer's
    structurally-identical Adam group shares one signature."""
    if op_type == 'fused_elementwise':
        names = {}

        def nid(n):
            if n not in names:
                names[n] = 'v%d' % len(names)
            return names[n]

        for n in attrs['arg_names']:
            nid(n)
        sub = []
        for so in attrs['sub_ops']:
            sub.append((
                so['type'],
                tuple(sorted((s, tuple(nid(n) for n in ns))
                             for s, ns in so['inputs'].items())),
                tuple(sorted((s, tuple(nid(n) for n in ns))
                             for s, ns in so['outputs'].items())),
                tuple(sorted((k, repr(v))
                             for k, v in so.get('attrs', {}).items()
                             if k not in _SKIP_ATTRS)),
                tuple(sorted(so.get('stop_grad') or ())),
            ))
        return ('fused', tuple(sub), tuple(nid(n)
                                           for n in attrs['out_names']))
    return tuple(sorted((k, _canonv(v)) for k, v in attrs.items()
                        if k not in _SKIP_ATTRS))


def _mesh_key(mesh):
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


# --------------------------------------------------------- emit context
class EmitCtx(object):
    """Kernel-facing ctx shim inside a memoized function.  Mirrors the
    OpCtx surface kernels actually use (rng / amp / mesh / is_infer /
    sub_ctx) but derives RNG keys from a TRACED (base_key, stream)
    pair: ``fold_in`` of equal uint32 values is bitwise equal whether
    the operand was a literal or an argument, so this matches OpCtx.rng
    exactly while keeping ``rng_stream`` out of the signature key."""

    is_infer = False
    __slots__ = ('_key', '_stream', '_op_type', 'amp', 'mesh')

    def __init__(self, key, stream, amp, mesh, op_type):
        self._key = key
        self._stream = stream
        self._op_type = op_type
        self.amp = amp
        self.mesh = mesh

    def rng(self, n=0):
        import jax
        if self._stream is None:
            raise EmitError(
                self._op_type,
                'kernel drew ctx.rng but the op type is not in the '
                'emitter RNG set (core/emit/emitter.RNG_OPS) — add it '
                'there so its stream base can be threaded')
        return jax.random.fold_in(self._key, self._stream + n)


def _op_streams(op, op_index):
    """Concrete uint32 fold-in bases for every RNG site of this op
    instance, in kernel draw order — (rng_stream attr, else the op's
    position), exactly OpCtx.rng's derivation.  Fused sub-ops inherit
    the FUSED op's op_index when unpinned, matching OpCtx.sub_ctx."""
    out = []
    if op.type in RNG_OPS:
        idx = op.attrs.get('rng_stream')
        if idx is None:
            idx = op_index
        out.append(np.uint32((idx + 1) * 1009))
    elif op.type == 'fused_elementwise':
        for sub in op.attrs['sub_ops']:
            if sub['type'] in RNG_OPS:
                idx = sub['attrs'].get('rng_stream')
                if idx is None:
                    idx = op_index
                out.append(np.uint32((idx + 1) * 1009))
    return tuple(out)


class _FusedEmitCtx(object):
    """Ctx handed to a fused_elementwise emit rule (the kernelgen
    tier): the traced base key, this op's pinned per-sub stream bases,
    and the policy flags the replay would have applied."""

    __slots__ = ('key', 'streams', 'amp', 'mesh')

    def __init__(self, key, streams, amp, mesh):
        self.key = key
        self.streams = streams
        self.amp = amp
        self.mesh = mesh


def _kg_token():
    """Kernelgen on/off + version: part of the memo key so flipping
    PT_KERNELGEN mid-process can't serve stale memoized functions."""
    try:
        from ...ops import kernelgen as _kg
        return _kg.config_token()
    except Exception:
        return None


def _replay_fused(ins, attrs, amp, mesh, key, streams):
    """Inline replay of a fused_elementwise sub-program (ops/fused.py
    semantics), dispatching each sub-op to its emit rule when one
    exists, else its kernel — no nested jit: per-sub pjit call overhead
    was measured to cancel the savings at Adam-group size."""
    import jax.numpy as jnp
    import jax.lax as lax
    from .. import executor as _ex
    xs = ins.get('X', [])
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    env = dict(zip(attrs['arg_names'], xs))
    si = 0
    for sub in attrs['sub_ops']:
        od = registry.get_op(sub['type'])
        fn = od.emit or od.impl
        ins2 = {}
        for slot, names in sub['inputs'].items():
            vals = [env[n] for n in names]
            ins2[slot] = vals if sub['input_is_list'].get(slot) else vals[0]
        if amp:
            ins2 = _ex._amp_sub_ins(sub['type'], ins2, amp)
        if sub['type'] in RNG_OPS:
            sctx = EmitCtx(key, streams[si], amp, mesh, sub['type'])
            si += 1
        else:
            sctx = EmitCtx(key, None, amp, mesh, sub['type'])
        outs = fn(sctx, ins2, sub['attrs']) or {}
        if amp:
            outs = _ex._amp_sub_outs(sub['type'], sub['attrs'], outs,
                                     amp)
        stop = set(sub.get('stop_grad') or ())
        for slot, names in sub['outputs'].items():
            if slot not in outs:
                continue
            vals = outs[slot]
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for name, val in zip(names, vals):
                if val is None:
                    continue
                if name in stop and hasattr(val, 'dtype') and \
                        jnp.issubdtype(val.dtype, jnp.floating):
                    val = lax.stop_gradient(val)
                env[name] = val
    return {'Out': [env[n] for n in attrs['out_names']]}


# ------------------------------------------------------- the fn memo
_MEMO = {}


def clear_memo():
    _MEMO.clear()


def _memo_fn(op, ins, amp, dmask, mesh):
    """Signature-keyed jitted pure function for one op shape.  The key
    deliberately EXCLUDES rng_stream (traced arg), stop-gradient var
    flags (applied outside, at the env write, like the traced path) and
    op position — the bench transformer's 232 ops land on ~30 keys."""
    import jax
    import jax.numpy as jnp
    from jax import tree_util as jtu
    from .. import executor as _ex
    use_amp = amp and op.type in _ex._AMP_OPS
    avals = jtu.tree_map(
        lambda x: (np.shape(x), str(jnp.result_type(x))), ins)
    dkey = tuple(sorted(dmask.items()))
    key = (op.type, _canon_attrs(op.type, op.attrs), _canonv(avals),
           use_amp, amp, op.type in _ex._REMAT_OPS, dkey, _mesh_key(mesh),
           _kg_token() if op.type == 'fused_elementwise' else None)
    fn = _MEMO.get(key)
    if fn is None:
        attrs = op.attrs
        otype = op.type
        fused = otype == 'fused_elementwise'
        od = registry.get_op(otype)
        rule = None if fused else (od.emit or od.impl)

        def pure_op(kw, bkey, streams):
            kw2 = {}
            for slot, vals in kw.items():
                if isinstance(vals, (list, tuple)):
                    kw2[slot] = [(_ex._amp_cast(v, jnp.bfloat16)
                                  if use_amp else v) for v in vals]
                else:
                    kw2[slot] = _ex._amp_cast(vals, jnp.bfloat16) \
                        if use_amp else vals
            if amp:
                kw2 = _ex._amp_match_ins(otype, kw2)
            if fused:
                if od.emit is not None:
                    outs = od.emit(_FusedEmitCtx(bkey, streams, amp,
                                                 mesh), kw2, attrs)
                else:
                    outs = _replay_fused(kw2, attrs, amp, mesh, bkey,
                                         streams)
            else:
                ctx = EmitCtx(bkey, streams[0] if streams else None,
                              amp, mesh, otype)
                outs = rule(ctx, kw2, attrs) or {}
            if use_amp and otype in _ex._AMP_CAST_OPS and outs and \
                    not attrs.get('amp_keep_bf16'):
                outs = {s: ([_ex._amp_cast(v, jnp.float32) for v in vs]
                            if isinstance(vs, (list, tuple))
                            else _ex._amp_cast(vs, jnp.float32))
                        for s, vs in outs.items()}
            pruned = {}
            for s, vs in outs.items():
                mm = dmask.get(s)
                if mm is None or not any(mm):
                    continue
                if isinstance(vs, (list, tuple)):
                    pruned[s] = [v if (i < len(mm) and mm[i]) else None
                                 for i, v in enumerate(vs)]
                else:
                    pruned[s] = vs if mm[0] else None
            return pruned

        if otype in _ex._REMAT_OPS:
            pure_op = jax.checkpoint(pure_op)
        fn = jax.jit(pure_op)
        _MEMO[key] = fn
    return fn


# ------------------------------------------------------------- engine
class EmitEngine(object):
    """Per-(program, feeds, fetches) emission state; see module doc."""

    def __init__(self, program, feed_names, fetch_names):
        from .. import executor as _ex
        self.program = program
        self.version = EMITTER_VERSION
        self._build_s = 0.0

        # 1. static coverage walk (all blocks) — first gap aborts
        coverage = {}
        for block in program.blocks:
            for op in block.ops:
                if op.type in coverage or op.type in _NATIVE:
                    continue
                ok, why = op_capability(op.type)
                if not ok:
                    raise EmitFallback(op.type, why)
                coverage[op.type] = why
                if op.type == 'fused_elementwise':
                    for sub in op.attrs['sub_ops']:
                        sok, swhy = op_capability(sub['type'])
                        if not sok:
                            raise EmitFallback(sub['type'],
                                               swhy + ' (fused sub-op)')
        self.coverage = tuple(sorted(coverage.items()))

        # 2. demanded-output analysis
        block = program.global_block()
        ops = block.ops
        required, written = _ex._analyze(block, feed_names, fetch_names)
        writeback = set(required | written)
        bw_idx = next((i for i, op in enumerate(ops)
                       if op.type == _ex._BACKWARD_OP), None)
        self.slim_fw_keep = None
        loss_name = None
        if bw_idx is not None:
            loss_name = ops[bw_idx].inputs['Loss'][0]
            fw_computed = set()
            for op in ops[:bw_idx]:
                fw_computed.update(op.output_names())
            post_needs, seen_w = set(), set()

            def _scan_reads(op_list):
                for op in op_list:
                    for n in op.input_names():
                        if n not in seen_w:
                            post_needs.add(n)
                    sb = op.attrs.get('sub_block')
                    if sb is not None:
                        _scan_reads(program.block(sb).ops)
                    for n in op.output_names():
                        seen_w.add(n)

            _scan_reads(ops[bw_idx + 1:])
            # writeback ∩ fw_computed matters: a persistable BOTH updated
            # pre-backward and written back (the LR decay counter) must
            # surface from the vjp'd forward or the step returns a stale
            # value (observed as an off-by-one in the decay schedule)
            self.slim_fw_keep = frozenset(
                ((post_needs | set(fetch_names) | writeback)
                 & fw_computed) | {loss_name})

        demanded = set(writeback) | set(fetch_names)
        demanded.update(n for n in (loss_name,) if n)
        if self.slim_fw_keep:
            demanded |= self.slim_fw_keep
        for b in program.blocks:
            for op in b.ops:
                demanded.update(op.input_names())
                if op.type not in _CONTROL_FLOW:
                    continue
                # native control-flow executors read env entries by
                # names carried in ATTRS (recurrent seq/init/update/out
                # vars, length_var, ...) and read back EVERY var their
                # sub-block writes (the while/cond carry machinery) —
                # none of which surfaces through input_names()
                for v in op.attrs.values():
                    if isinstance(v, str):
                        demanded.add(v)
                    elif isinstance(v, (list, tuple)):
                        demanded.update(
                            x for x in v if isinstance(x, str))
                stack = [op.attrs.get('sub_block')]
                seen_sb = set()
                while stack:
                    sb = stack.pop()
                    if sb is None or sb in seen_sb:
                        continue
                    seen_sb.add(sb)
                    for sop in program.block(sb).ops:
                        demanded.update(sop.output_names())
                        stack.append(sop.attrs.get('sub_block'))
        self._dmasks = {}
        for b in program.blocks:
            for op in b.ops:
                self._dmasks[id(op)] = {
                    s: tuple(n in demanded for n in names)
                    for s, names in op.outputs.items()}

    def fingerprint_extra(self):
        """Joins the AOT disk fingerprint: emitter version + the
        program's coverage set with each op's emission mode."""
        return ('emitter', self.version, self.coverage)

    def take_build_seconds(self):
        """Accumulated memo-build + dispatch wall time (the `emit_s`
        half of the old trace_s) since construction/last take."""
        s, self._build_s = self._build_s, 0.0
        return s

    def run_op(self, op, op_index, env, ectx):
        """Emit one op into `env` under the outer trace (called from
        executor._exec_ops_plain in place of kernel tracing)."""
        import jax.numpy as jnp
        import jax.lax as lax
        dmask = self._dmasks.get(id(op))
        if dmask is None or getattr(ectx, 'forensic', None) is not None:
            # op object outside the analyzed program — or a forensic
            # probe lowering, where every output must materialize so the
            # per-op finite probes have something to look at (dead-op
            # elision would hide exactly the op being hunted)
            dmask = {s: tuple(True for _ in names)
                     for s, names in op.outputs.items()}
        if op.type not in EFFECTFUL_OPS and \
                not any(any(mm) for mm in dmask.values()):
            return   # dead op instance: nothing downstream can see it
        ins = {}
        for slot, names in op.inputs.items():
            vals = [env[n] for n in names]
            ins[slot] = vals if op.input_is_list[slot] else vals[0]
        streams = _op_streams(op, op_index)
        t0 = time.perf_counter()
        fn = _memo_fn(op, ins, getattr(ectx, 'amp', False), dmask,
                      ectx.mesh)
        outs = fn(ins, ectx.base_key, streams)
        self._build_s += time.perf_counter() - t0
        for slot, names in op.outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for name, val in zip(names, vals):
                if val is None:
                    continue
                var = op.block._find_var_recursive(name)
                if var is not None and var.stop_gradient and \
                        hasattr(val, 'dtype') and \
                        jnp.issubdtype(val.dtype, jnp.floating):
                    val = lax.stop_gradient(val)
                env[name] = val
