"""Direct Program→jaxpr emitter: trace-free cold starts.

PR-5's trace/compile split proved cold-start time is dominated by per-op
``jnp`` primitive dispatch inside the kernels — cutting 57% of program
ops barely moved ``jit.lower()`` — so this package bypasses per-op
Python tracing: the optimized Program IR lowers through **memoized,
signature-keyed jitted op functions** (pjit call eqns in the outer
jaxpr).  The bench transformer's 232 ops collapse onto ~30 distinct
(op type, canonical attrs, input avals, AMP mode, demanded outputs)
signatures, each traced ONCE per process; everything after the first
occurrence is a cached function application.  Hand raw-``lax`` emit
rules (rules.py, registered via ``registry.register_emit``) skip kernel
tracing entirely for the hottest signatures; the kernel stays the
semantic reference (tests sweep rule vs kernel bitwise).

Env contract:

* ``PT_EMIT=1`` (default) — emit-mode lowering with per-program
  fallback to the traced path on any unsupported op (loud: warn-once +
  ``emitter.fallbacks`` counters, mirroring ops/_fallback.py).
* ``PT_EMIT=0`` — classic traced lowering.
* ``PT_STRICT_EMIT=1`` — a fallback raises instead, naming the first
  unsupported op (CI posture; ci_smoke holds all 12 zoo programs to
  zero fallbacks under it).

Parity is bitwise (losses AND end-of-run param/optimizer state) because
emission replicates the executor's per-op policies inside each memoized
function — AMP casts, ``_amp_match_ins``, cast-back, per-sub-op
stop-gradient — and RNG sites receive their fold-in stream bases as
*traced arguments*, so ``fold_in(base_key, stream + n)`` matches the
kernel's ``ctx.rng`` derivation exactly while ops that differ only in
``rng_stream`` share one compiled signature.

Fingerprint interaction (core/compile_cache): emitted executables join
the AOT disk cache keyed with ``extra=(EMITTER_VERSION, coverage set)``
— the per-program set of (op type, rule-or-kernel) emission modes — so
bumping the emitter or flipping one op between rule and kernel emission
invalidates exactly the affected entries.  A program that *falls back*
fingerprints with ``extra=None`` and therefore SHARES disk artifacts
with ``PT_EMIT=0`` runs.
"""
import os

from ... import observability as _obs

__all__ = ['enabled', 'strict', 'config_token', 'EMITTER_VERSION',
           'EmitFallback', 'EmitError', 'build_engine', 'unsupported_ops',
           'note_fallback', 'clear_memo', 'reset_fallbacks']

# bump on any change to emission semantics/keying — it joins the AOT
# disk fingerprint, so stale emitted executables can never be served
EMITTER_VERSION = 1


def enabled():
    return os.environ.get('PT_EMIT', '1') not in ('0', 'false', 'False')


def strict():
    return os.environ.get('PT_STRICT_EMIT', '0') in ('1', 'true', 'True')


def config_token():
    """Joins the executor hot key and the launch signature's ``emit``
    component: toggling PT_EMIT mid-process must read as a NAMED
    signature change (same pattern as the PT_OPT config token)."""
    return ('emit', 1 if enabled() else 0, EMITTER_VERSION)


class EmitFallback(Exception):
    """Static coverage gap found while building the engine: the program
    contains an op the emitter cannot lower.  Non-strict mode catches
    this per program and falls back to traced lowering."""

    def __init__(self, op, why):
        self.op = op
        self.why = why
        super(EmitFallback, self).__init__(
            'op "%s" is not emit-capable: %s' % (op, why))


class EmitError(Exception):
    """Runtime emission failure (raised mid-trace), e.g. an op outside
    the known RNG set drew from ``ctx.rng``.  The executor catches it,
    notes the fallback, and rebuilds the program on the traced path."""

    def __init__(self, op, why):
        self.op = op
        self.why = why
        super(EmitError, self).__init__(
            'emitting op "%s" failed: %s' % (op, why))


# ------------------------------------------------- loud degradation
# mirrors ops/_fallback.py kernel_fallback: silent degradation is how
# perf regressions hide — every program-level fallback warns ONCE per
# op type and bumps counters bench telemetry gates on
_warned = set()


def note_fallback(op, why):
    import warnings
    _obs.metrics.counter('emitter.fallbacks').inc()
    _obs.metrics.counter('emitter.fallbacks.%s' % op).inc()
    if _obs.enabled():
        _obs.tracing.instant('emitter.fallback', cat='compile',
                             args={'op': op, 'why': str(why)[:256]})
    if op not in _warned:
        _warned.add(op)
        warnings.warn(
            'direct emitter fell back to traced lowering on op "%s": %s '
            '(PT_STRICT_EMIT=1 raises instead; PT_EMIT=0 silences)'
            % (op, why), RuntimeWarning, stacklevel=3)


def reset_fallbacks():
    """Test hook: forget the warn-once set."""
    _warned.clear()


def build_engine(program, feed_names, fetch_names):
    """Static coverage walk + demanded-output analysis for one optimized
    program.  Raises EmitFallback on the first unsupported op."""
    from . import emitter
    return emitter.EmitEngine(program, feed_names, fetch_names)


def unsupported_ops(program):
    """[(op_type, why)] over all blocks — the static gap list pt_lint's
    D015 pass renders (same capability test the engine applies)."""
    from . import emitter
    return emitter.unsupported_ops(program)


def clear_memo():
    """Test hook: drop the process-wide memoized op functions."""
    from . import emitter
    emitter.clear_memo()
