"""Hand raw-``lax`` emit rules for the hottest signatures.

Per-signature build profiling on the bench transformer (PERF.md) put
~75% of memo-build time in tracing kernel impls through ``jnp`` — the
Adam ``fused_elementwise`` group (158 sub-ops) alone cost 0.9s.  These
rules mirror each kernel's primitive DAG directly in ``lax``, skipping
``jnp``'s dispatch/promotion layers: same DAG → same XLA program → same
bits (IEEE ops are commutative in operand *naming*, not evaluation
order — the order here matches the kernel exactly).

Rules are a PERF OVERLAY, not a second semantics: every rule is swept
against its kernel bitwise in tests/test_emitter.py, and the emitter's
coverage set marks rule-vs-kernel emission per op type in the AOT
fingerprint.  Guidelines for adding one:

* mirror the kernel line-for-line; ``jnp.square`` is
  ``lax.integer_pow(x, 2)``; use ``jnp.multiply`` (not ``lax.mul``)
  where operand ranks may differ (lax requires equal shapes);
* scalar python-float operands promote identically under lax and jnp;
* elementwise rules take the lax fast path only on exact shape+dtype
  match and defer to the kernel's ``jnp`` expression otherwise;
* ops built on ``custom_jvp``/``custom_vjp`` wrappers (relu, the
  attention kernels) keep their kernels — the wrapper IS the fast path.
"""
import jax.numpy as jnp
from jax import lax

from ..registry import get_op, register_emit
from ...ops.math import _bcast_y

__all__ = []


@register_emit('adam')
def adam(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    m1, m2 = ins['Moment1'], ins['Moment2']
    b1p, b2p = ins['Beta1Pow'], ins['Beta2Pow']
    if not (p.dtype == g.dtype == m1.dtype == m2.dtype):
        # mixed precision (bf16 grads over f32 moments): lax requires
        # equal dtypes where the kernel's jnp ops promote — defer
        return get_op('adam').impl(ctx, ins, attrs)
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    lr = lax.reshape(ins['LearningRate'], ())
    m1n = lax.add(lax.mul(b1, m1), lax.mul(1 - b1, g))
    m2n = lax.add(lax.mul(b2, m2), lax.mul(1 - b2, lax.integer_pow(g, 2)))
    lr_t = lax.div(
        lax.mul(lr, lax.sqrt(lax.sub(1.0, lax.reshape(b2p, ())))),
        lax.sub(1.0, lax.reshape(b1p, ())))
    pn = lax.sub(p, lax.div(jnp.multiply(lr_t, m1n),
                            lax.add(lax.sqrt(m2n), eps)))
    return {'ParamOut': pn, 'Moment1Out': m1n, 'Moment2Out': m2n,
            'Beta1PowOut': lax.mul(b1p, b1),
            'Beta2PowOut': lax.mul(b2p, b2)}


@register_emit('reshape')
def reshape(ctx, ins, attrs):
    x = ins['X']
    out_shape = [x.shape[i] if d == 0 else int(d)
                 for i, d in enumerate(attrs['shape'])]
    return {'Out': x.reshape(out_shape), 'XShape': None}


@register_emit('transpose')
def transpose(ctx, ins, attrs):
    return {'Out': lax.transpose(ins['X'], tuple(attrs['axis'])),
            'XShape': None}


def _ew_rule(name, lax_fn, jnp_fn):
    @register_emit(name)
    def rule(ctx, ins, attrs, _lax=lax_fn, _jnp=jnp_fn):
        x, y = ins['X'], ins['Y']
        y = _bcast_y(x, y, attrs.get('axis', -1))
        if getattr(x, 'shape', None) == getattr(y, 'shape', ()) and \
                getattr(x, 'dtype', 0) == getattr(y, 'dtype', 1):
            return {'Out': _lax(x, y)}
        return {'Out': _jnp(x, y)}
    return rule


_ew_rule('elementwise_add', lax.add, lambda x, y: x + y)
_ew_rule('elementwise_sub', lax.sub, lambda x, y: x - y)
_ew_rule('elementwise_mul', lax.mul, lambda x, y: x * y)
_ew_rule('elementwise_div', lax.div, lambda x, y: x / y)
