"""First-class sharding annotations for the Program IR.

A *spec* mirrors `jax.sharding.PartitionSpec`: one entry per tensor
dimension, where each entry is None (replicated), a mesh-axis name, or a
tuple of mesh-axis names (that dimension is split over the product of
those axes).  The canonical in-IR form is a plain tuple so specs hash,
compare, and round-trip through `program_to_desc` byte-stably — the desc
layer stores the `spec_to_jsonable` form (nested lists), and
`desc_to_program` restores the tuple form via `spec_from_jsonable`.

`Variable.sharding` (core/framework.py) stores the canonical form and
syncs `Program._sharding` (the executor's in_shardings source) with the
PartitionSpec view, so annotating a var once serves both the lint passes
(analysis/passes/sharding.py) and the lowering path.
"""


def normalize_spec(spec):
    """Canonicalize any accepted spec spelling to a tuple (or None).

    Accepts None, a PartitionSpec, a single axis-name string, or a
    sequence whose entries are None / str / sequence-of-str.
    """
    if spec is None:
        return None
    if isinstance(spec, str):
        return (spec,)
    entries = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            entries.append(e)
        else:
            sub = tuple(e)
            for a in sub:
                if not isinstance(a, str):
                    raise TypeError(
                        'sharding spec entries must be None, a mesh-axis '
                        'name, or a tuple of names; got %r' % (e,))
            entries.append(sub)
    return tuple(entries)


def spec_to_jsonable(spec):
    """Canonical tuple spec -> JSON-stable form (nested lists)."""
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def spec_from_jsonable(obj):
    """Inverse of spec_to_jsonable."""
    if obj is None:
        return None
    return tuple(tuple(e) if isinstance(e, list) else e for e in obj)


def to_partition_spec(spec):
    """Canonical spec -> jax.sharding.PartitionSpec (None passes through)."""
    if spec is None:
        return None
    from jax.sharding import PartitionSpec
    return PartitionSpec(*spec)


def spec_axes(spec):
    """The set of mesh-axis names a spec references."""
    axes = set()
    for e in (spec or ()):
        if e is None:
            continue
        if isinstance(e, str):
            axes.add(e)
        else:
            axes.update(e)
    return axes


def spec_divisor(spec, mesh_axes):
    """How many devices one shard of a spec'd tensor is divided over:
    the product of the mesh sizes of every referenced axis.  `mesh_axes`
    is a name->size dict (or None -> divisor 1); axes the mesh does not
    declare count as 1 (D019 reports them separately)."""
    if not spec or not mesh_axes:
        return 1
    d = 1
    for a in spec_axes(spec):
        d *= int(mesh_axes.get(a, 1))
    return max(1, d)


def specs_equal(a, b):
    """Spec equality on the canonical form (None == all-replicated is
    NOT assumed: None means 'unannotated', which merges with anything)."""
    return normalize_spec(a) == normalize_spec(b)
