"""Scope + Executor: lower a whole Block to ONE jitted XLA executable.

Capability parity with reference python/paddle/fluid/executor.py and the C++
paddle/fluid/framework/executor.cc — redesigned TPU-first.  The reference
interprets a ProgramDesc op-by-op, dispatching a CUDA kernel per OpDesc; here
the entire block (forward, vjp backward, optimizer updates) is traced into a
single jitted function, so one `exe.run()` is one device launch.  Parameters
live on device in a Scope and are donated to the executable, so updates are
in-place (input/output buffer aliasing) with zero copies.
"""
import os
import time

import numpy as np

from . import registry
from . import async_runtime as _async
from . import compile_cache as _cc
from . import emit as _emit
from . import passes as _passes
from .framework import Variable, default_main_program, TPUPlace
from .. import observability as _obs
from ..testing import faults as _faults

__all__ = ['Executor', 'Scope', 'scope_guard', 'global_scope']

# ops the executor handles natively (no registry impl)
_BACKWARD_OP = '__backward__'
from .control_flow_exec import NATIVE_OPS as _CONTROL_FLOW

import itertools

_scope_serial = itertools.count()


class Scope(object):
    """name -> on-device jax.Array holder for persistable variables.

    Parity: paddle/fluid/framework/scope.{h,cc}.  Flat (the reference's
    scope hierarchy existed for per-thread local scopes in the parallel
    executor; with a single XLA executable temporaries never materialize).
    `_serial` is a process-unique id used in the executor's lowering-cache
    key — unlike id(), it can never be recycled by a later Scope."""

    def __init__(self):
        self.vars = {}
        self._serial = next(_scope_serial)

    def var(self, name):
        return self

    def find_var(self, name):
        return _VarHandle(self, name) if name in self.vars else None

    def set(self, name, value):
        self.vars[name] = value

    def get(self, name):
        return self.vars[name]

    def keys(self):
        return self.vars.keys()

    def __contains__(self, name):
        return name in self.vars


class _VarHandle(object):
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self._scope.vars[self._name]

    def set(self, value, place=None):
        self._scope.vars[self._name] = np.asarray(value)


_global_scope = Scope()


def global_scope():
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _stack_feeds(per_step):
    """Stack K per-step feed dicts on a new leading [K] axis.  Host arrays
    stack on host (one device_put per superbatch, not per step); if any
    step's value is already a device array the stack happens on device."""
    stacked = {}
    for k in per_step[0]:
        vals = [f[k] for f in per_step]
        if any(hasattr(v, 'devices') for v in vals):
            import jax.numpy as jnp
            stacked[k] = jnp.stack(vals)
        else:
            stacked[k] = np.stack(vals)
    return stacked


def _zero_cotangent(v):
    import jax
    import jax.numpy as jnp
    if jnp.issubdtype(v.dtype, jnp.floating) or jnp.issubdtype(
            v.dtype, jnp.complexfloating):
        return jnp.zeros_like(v)
    return np.zeros(v.shape, dtype=jax.dtypes.float0)


# MXU-bound ops worth running in bfloat16 under AMP (matmul/conv class):
# their f32 inputs cast down to bf16.  What happens to the OUTPUT is
# per-class, decided by measurement on TPU v5 lite (see PERF.md):
#   - conv class: outputs STAY bf16 ("flow-through") — activations keep
#     half-width through the BN/relu/residual chains, halving HBM traffic.
#     ResNet-50 measured +25% img/s from this alone.
#   - matmul/attention class: outputs cast back to f32 (the cast fuses
#     into the GEMM epilogue).  Flow-through measured 4% SLOWER on the
#     transformer: its hot f32 consumers (layer_norm stats, the CE
#     logsumexp) upcast anyway, so bf16 outputs only add VPU cast work.
# Numerics-sensitive ops (norm statistics, softmax, cross-entropy)
# upcast internally to f32 in their impls, so precision-critical
# reductions never run in bf16 either way.
# PT_AMP_FLOW=0 / PT_AMP_FLOW=all override the split for A/B runs.
_AMP_CAST_OPS = {'mul', 'matmul', 'flash_attention', 'ring_attention',
                 'bilinear_tensor_product'}
_AMP_FLOW_OPS = {'conv2d', 'conv3d', 'conv2d_transpose',
                 'conv3d_transpose', 'sequence_conv'}
_flow_env = os.environ.get('PT_AMP_FLOW', 'conv')
if _flow_env == '0':
    _AMP_CAST_OPS = _AMP_CAST_OPS | _AMP_FLOW_OPS
    _AMP_FLOW_OPS = set()
elif _flow_env == 'all':
    _AMP_FLOW_OPS = _AMP_FLOW_OPS | _AMP_CAST_OPS
    _AMP_CAST_OPS = set()
_AMP_OPS = _AMP_CAST_OPS | _AMP_FLOW_OPS

# Elementwise glue: under AMP, if any float input is already bf16, cast
# the f32 ones down instead of letting numpy promotion drag the chain
# back to f32 (conv bias adds, CNN residual adds).  Scalar-only f32
# chains (LR schedules, loss reductions) have no bf16 input and are
# untouched.
_AMP_MATCH = {'elementwise_add', 'elementwise_sub', 'elementwise_mul',
              'elementwise_div', 'elementwise_max', 'elementwise_min'}

# Rematerializing softmax_with_cross_entropy (jax.checkpoint so the f32
# [B, T, V] log-prob residual never persists to backward) was measured
# 19% SLOWER end-to-end on TPU v5 lite (PERF.md): the recomputed
# logsumexp pass costs more than the saved HBM round-trip at bench
# shapes.  Kept behind PT_CE_REMAT=1 for re-testing on other parts.
_REMAT_OPS = ({'softmax_with_cross_entropy'}
              if os.environ.get('PT_CE_REMAT', '0') == '1' else set())


def _amp_cast(x, to):
    import jax.numpy as jnp
    if hasattr(x, 'dtype') and x.dtype == (
            jnp.float32 if to == jnp.bfloat16 else jnp.bfloat16):
        return x.astype(to)
    return x


def _amp_match_ins(op_type, ins):
    """The elementwise-glue half of the AMP policy (see _AMP_MATCH): if
    any float input is already bf16, cast the f32 ones down.  Shared by
    the trace loop below and the fused_elementwise replay (ops/fused.py),
    which must apply the identical policy per sub-op."""
    import jax.numpy as jnp
    if op_type not in _AMP_MATCH:
        return ins
    if not any(getattr(v, 'dtype', None) == jnp.bfloat16
               for v in ins.values() if not isinstance(v, (list, tuple))):
        return ins
    return {s: (v if isinstance(v, (list, tuple))
                else _amp_cast(v, jnp.bfloat16))
            for s, v in ins.items()}


def _amp_sub_ins(op_type, ins, amp):
    """The FULL per-op AMP input policy the trace loop below applies,
    for replayed sub-ops (ops/fused.py, the emitter's _replay_fused, the
    kernelgen dedicated steps): _AMP_OPS get every input cast to bf16
    before dispatch, then the elementwise-match glue runs.  A fused
    group containing e.g. flash_attention must see the same activations
    it would have unfused."""
    import jax.numpy as jnp
    if not amp:
        return ins
    if op_type in _AMP_OPS:
        ins = {s: ([_amp_cast(v, jnp.bfloat16) for v in vs]
                   if isinstance(vs, (list, tuple))
                   else _amp_cast(vs, jnp.bfloat16))
               for s, vs in ins.items()}
    return _amp_match_ins(op_type, ins)


def _amp_sub_outs(op_type, attrs, outs, amp):
    """The cast-back half: _AMP_CAST_OPS outputs return to f32 unless
    the op carries the amp_keep_bf16 opt-out — exactly the trace loop's
    policy, applied at the sub-op granularity of a fused replay."""
    import jax.numpy as jnp
    if not (amp and op_type in _AMP_CAST_OPS and outs) \
            or attrs.get('amp_keep_bf16'):
        return outs
    return {s: ([_amp_cast(v, jnp.float32) for v in vs]
                if isinstance(vs, (list, tuple))
                else _amp_cast(vs, jnp.float32))
            for s, vs in outs.items()}


class ForensicProbes(object):
    """Trace-time collector for the per-op finite-probe lowering
    (train/forensics.py, PT_FORENSIC).

    While a forensic lowering traces, every op's inexact outputs get a
    3-vector probe [all_finite, nonfinite_count, max_abs_finite] written
    into the active environment under a reserved ``__fprobe_K__`` name.
    Riding the environment is what lets forward-op probes cross the vjp
    boundary as ordinary primal outputs (stop_gradient'd, zero
    cotangent) instead of leaking tracers.  ``meta`` records, in
    allocation order, which (op position, op type, output var,
    source_loc) each probe slot describes — the python-side key that
    turns the fetched [N, 3] stack back into a named verdict."""

    PREFIX = '__fprobe_'

    def __init__(self):
        self.meta = []
        self.env = None    # the environment dict currently being traced

    def begin(self):
        self.meta = []
        self.env = None

    def names(self):
        return ['%s%d__' % (self.PREFIX, i) for i in range(len(self.meta))]

    def note(self, pos, op_type, var_name, source_loc, val):
        import jax
        import jax.numpy as jnp
        if self.env is None or not (
                hasattr(val, 'dtype') and
                jnp.issubdtype(val.dtype, jnp.inexact)):
            return
        name = '%s%d__' % (self.PREFIX, len(self.meta))
        try:
            loc = '%s:%s' % tuple(source_loc) if source_loc else ''
        except TypeError:
            loc = str(source_loc)
        self.meta.append({'pos': int(pos), 'op_type': op_type,
                          'var': var_name, 'source_loc': loc})
        fin = jnp.isfinite(val)
        mag = jnp.abs(val).astype(jnp.float32)
        probe = jnp.stack([
            jnp.all(fin).astype(jnp.float32),
            jnp.sum(jnp.logical_not(fin)).astype(jnp.float32),
            jnp.max(jnp.where(fin, mag, jnp.zeros_like(mag)), initial=0.0),
        ])
        self.env[name] = jax.lax.stop_gradient(probe)

    def note_op(self, env, pos, op):
        """Probe every inexact output `op` just wrote into `env`."""
        self.env = env
        loc = getattr(op, 'source_loc', None)
        for nm in op.output_names():
            v = env.get(nm)
            if v is not None:
                self.note(pos, op.type, nm, loc, v)


def _exec_ops(ops, op_offset, env, ectx, program):
    """Trace a run of registered ops into `env` (the heart of lowering).
    Contiguous runs of ops sharing a recompute_id execute under
    jax.checkpoint: their activations are rematerialized in the backward
    pass instead of saved (see framework.recompute_scope)."""
    import jax
    if getattr(ectx, 'forensic', None) is not None:
        # forensic probe mode: no jax.checkpoint recompute grouping —
        # probe values written inside a checkpointed group could never
        # escape it to the step function's outputs
        _exec_ops_plain(ops, op_offset, env, ectx, program)
        return
    i = 0
    n = len(ops)
    while i < n:
        rid = ops[i].attrs.get('recompute_id')
        if rid is None or ops[i].type in _CONTROL_FLOW:
            _exec_ops_plain(ops[i:i + 1], op_offset + i, env, ectx, program)
            i += 1
            continue
        j = i
        while j < n and ops[j].attrs.get('recompute_id') == rid and \
                ops[j].type not in _CONTROL_FLOW:
            j += 1
        group = ops[i:j]
        reads = set()
        writes = []
        produced = set()
        for op in group:
            for nm in op.input_names():
                if nm not in produced:
                    reads.add(nm)
            for nm in op.output_names():
                produced.add(nm)
                writes.append(nm)
        ext_in = {nm: env[nm] for nm in reads if nm in env}

        def grp_fn(ins, _group=group, _off=op_offset + i, _w=writes):
            env2 = dict(ins)
            _exec_ops_plain(_group, _off, env2, ectx, program)
            return {nm: env2[nm] for nm in _w if nm in env2}

        env.update(jax.checkpoint(grp_fn)(ext_in))
        i = j


def _exec_ops_plain(ops, op_offset, env, ectx, program):
    import jax
    import jax.lax as lax
    import jax.numpy as jnp
    amp = getattr(program, '_amp', False)
    # direct-emit mode (core/emit): _lower attached an EmitEngine to the
    # ExecCtx — ops lower through memoized per-signature functions
    # instead of per-op kernel tracing.  Control flow stays native (its
    # bodies re-enter here, engine in tow).
    engine = getattr(ectx, 'emit_engine', None)
    fx = getattr(ectx, 'forensic', None)
    for i, op in enumerate(ops):
        if fx is not None:
            # point the collector at the live env BEFORE dispatch so
            # impls that probe internally (fused_elementwise sub-ops)
            # write their probes where the step outputs can see them
            fx.env = env
        if op.type in _CONTROL_FLOW:
            from . import control_flow_exec
            control_flow_exec.exec_control_flow_op(
                op, env, ectx, op_offset + i, program)
            if fx is not None:
                fx.note_op(env, op_offset + i, op)
            continue
        if engine is not None:
            engine.run_op(op, op_offset + i, env, ectx)
            if fx is not None:
                # emit mode probes at op granularity (the memoized fns
                # never see the collector); sub-program granularity for
                # fused groups comes from the plain-trace forensic
                # runner, which is what train/forensics.py lowers
                fx.note_op(env, op_offset + i, op)
            continue
        impl = registry.get_op(op.type).impl
        use_amp = amp and op.type in _AMP_OPS
        ins = {}
        for slot, names in op.inputs.items():
            vals = [env[n] for n in names]
            if use_amp:
                vals = [_amp_cast(v, jnp.bfloat16) for v in vals]
            ins[slot] = vals if op.input_is_list[slot] else vals[0]
        if amp:
            ins = _amp_match_ins(op.type, ins)
        ctx = ectx.for_op(op_offset + i, op)
        if op.type in _REMAT_OPS:
            outs = jax.checkpoint(
                lambda kw, _impl=impl, _ctx=ctx, _a=op.attrs:
                _impl(_ctx, kw, _a))(ins)
        else:
            outs = impl(ctx, ins, op.attrs)
        # amp_keep_bf16: per-op opt-out of the cast-back policy for a
        # GEMM whose consumers are bf16-tolerant (e.g. the logit
        # projection feeding softmax_with_cross_entropy, which upcasts
        # its reductions internally) — halves that [B, T, V] buffer
        if use_amp and op.type in _AMP_CAST_OPS and outs and \
                not op.attrs.get('amp_keep_bf16'):
            outs = {s: ([_amp_cast(v, jnp.float32) for v in vs]
                        if isinstance(vs, (list, tuple))
                        else _amp_cast(vs, jnp.float32))
                    for s, vs in outs.items()}
        if outs is None:
            outs = {}
        for slot, names in op.outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for name, val in zip(names, vals):
                if val is None:
                    continue
                var = op.block._find_var_recursive(name)
                if var is not None and var.stop_gradient and hasattr(
                        val, 'dtype') and jnp.issubdtype(
                            val.dtype, jnp.floating):
                    val = lax.stop_gradient(val)
                env[name] = val
        if fx is not None and op.type != 'fused_elementwise':
            # fused groups probe themselves at sub-program granularity
            # (ops/fused.py) — an outer probe would double-count
            fx.note_op(env, op_offset + i, op)


def _analyze(block, feed_names, fetch_names):
    """Static analysis: which persistables must come from scope, which get
    written back.  Recurses into control-flow sub-blocks: a persistable
    referenced anywhere inside a while/conditional body (even write-only —
    it's a loop carry needing an initial value) counts as required."""
    program = block.program
    persistable = set()
    for b in program.blocks:
        persistable |= {n for n, v in b.vars.items() if v.persistable}
    written = set()
    required = set()
    feed = set(feed_names)

    def visit_read(n):
        if n in persistable and n not in written and n not in feed:
            required.add(n)

    def visit_block(b, is_sub):
        for op in b.ops:
            for n in op.input_names():
                visit_read(n)
            if op.type == _BACKWARD_OP:
                for p in op.attrs['params']:
                    visit_read(p)
            sb = op.attrs.get('sub_block')
            if sb is not None:
                visit_block(program.block(sb), True)
            for n in op.output_names():
                if is_sub:
                    visit_read(n)
                if n in persistable:
                    written.add(n)

    visit_block(block, False)
    for n in fetch_names:
        visit_read(n)
    return required, written


# traces completed by _lower-built functions — a python-side effect that
# runs once per jit trace, so tests can assert "retraced exactly once per
# cache key" directly instead of inferring it from cache sizes
_TRACE_COUNT = [0]

_program_serial_counter = itertools.count()


def _program_serial(program):
    """Process-unique program id for telemetry: unlike id(), never recycled,
    and paired with _version so an in-place program edit reads as a change."""
    serial = getattr(program, '_obs_serial', None)
    if serial is None:
        serial = next(_program_serial_counter)
        program._obs_serial = serial
    return (serial, program._version)


def _launch_signature(program, feed_vals, feed_names, fetch_names, steps,
                      check_nan, scope):
    """Every component the lowering cache (and jax.jit under it) keys on,
    structured so the retrace explainer can name what changed."""
    return _obs.LaunchSignature(
        program=_program_serial(program),
        feed_shapes={n: tuple(np.shape(feed_vals[n])) for n in feed_names},
        feed_dtypes={n: str(getattr(feed_vals[n], 'dtype',
                                    type(feed_vals[n]).__name__))
                     for n in feed_names},
        fetch_set=fetch_names, steps=steps, check_nan=check_nan,
        scope=scope._serial, opt=_passes.config_token(),
        emit=_emit.config_token(), kernelgen=_kg_token())


def _kg_token():
    from ..ops import kernelgen as _kg
    return _kg.config_token()


def _compose_fp_extra(engine_extra):
    """Compose the emitter's fingerprint extra with kernelgen's.  When
    kernelgen is off the engine extra passes through UNCHANGED (same
    fingerprints as before the tier existed — disk artifacts stay
    shared); when on, both paths gain the kernelgen component."""
    from ..ops import kernelgen as _kg
    if not _kg.enabled():
        return engine_extra
    kx = _kg.fingerprint_extra()
    return (engine_extra, kx) if engine_extra is not None else kx


def _lower(program, feed_names, fetch_names, donate=True, mesh=None,
           out_shardings_for=None, check_nan=False, steps=None,
           emit_engine=None, forensic=None):
    """Build the jitted step function for (program, feeds, fetches).
    check_nan compiles a fused all-finite flag over fetches+updates INTO
    the executable (per-array host checks measured >30x slower through
    the device tunnel — see PERF.md); run_fn then returns a third
    output, one bool scalar.

    steps=None lowers the classic one-step executable.  steps=K lowers K
    training iterations into ONE executable: a lax.scan over feeds
    stacked on a leading [K] axis, parameter/optimizer state threaded as
    the (donated) carry, per-step RNG derived by folding `counter + i`
    into the program seed (bitwise-identical to K sequential runs, which
    consume counters counter..counter+K-1), fetches stacked per step,
    and the check_nan flag AND-reduced across the scan.

    forensic=ForensicProbes() builds the PT_FORENSIC probe variant: the
    step function additionally returns a stacked [N, 3] array of per-op
    finite probes (see ForensicProbes) whose rows line up with
    ``forensic.meta`` after the first trace.  One-step lowerings only —
    forensic replay walks the window a step at a time by design."""
    import jax
    import jax.numpy as jnp

    if forensic is not None and steps is not None:
        raise ValueError('forensic lowering is single-step only '
                         '(steps must be None)')

    # Static analysis at the lowering-cache miss (SSA-graph race
    # detection analog, SURVEY §2.8, grown into the full pt-lint pass
    # suite): def-use ordering bugs, shape/dtype mismatches, donation
    # conflicts etc. fail at build with the op+var named, not mid-trace.
    # PT_LINT=strict (default) raises on error findings; =warn demotes
    # them to one LintWarning; =0 restores the raw mid-trace failures.
    # An optimizer-produced twin (core/passes) skips the hook: its RAW
    # original was already linted — gating on the rewritten program
    # would let DCE delete a user's bug before strict mode could name it.
    if not getattr(program, '_opt_of', False):
        from ..analysis import apply_lint_policy, lint_mode
        apply_lint_policy(program, feed_names=feed_names,
                          fetch_names=fetch_names, mode=lint_mode(),
                          header='program lint failed before lowering')

    block = program.global_block()
    ops = block.ops
    required, written = _analyze(block, feed_names, fetch_names)
    params_in = sorted(required)
    writeback = sorted((required | written))
    bw_idx = next((i for i, op in enumerate(ops)
                   if op.type == _BACKWARD_OP), None)

    def step_fn(params, feeds, counter):
        _TRACE_COUNT[0] += 1
        # the run counter is FOLDED into the program key rather than mixed
        # arithmetically into the seed: inside a K-step scan the per-step
        # key is fold_in(key, counter + i), which is exactly what the i-th
        # sequential run would derive — multi-step and single-step paths
        # share one RNG stream by construction
        base_key = jax.random.fold_in(
            jax.random.key(program.random_seed), counter)
        ectx = registry.ExecCtx(base_key, mesh=mesh,
                                amp=getattr(program, '_amp', False))
        if emit_engine is not None:
            ectx.emit_engine = emit_engine
        if forensic is not None:
            forensic.begin()   # a retrace must not duplicate probe meta
            ectx.forensic = forensic
        env0 = {}
        env0.update(feeds)
        env0.update(params)

        if bw_idx is None:
            env = dict(env0)
            _exec_ops(ops, 0, env, ectx, program)
        else:
            bw_op = ops[bw_idx]
            pnames = bw_op.attrs['params']
            loss_name = bw_op.inputs['Loss'][0]
            missing = [p for p in pnames if p not in env0]
            if missing:
                raise ValueError(
                    '__backward__ wrt non-leaf vars %s not supported yet; '
                    'differentiate wrt parameters or feed vars' % missing)
            diff = {p: env0[p] for p in pnames}
            rest = {k: v for k, v in env0.items() if k not in diff}
            # Prune fw's outputs to what the rest of the step actually
            # reads.  Returning the whole env would make EVERY
            # intermediate a vjp primal output carrying a dense zero
            # cotangent through the transpose — measured on the per-HLO
            # ledger (PERF.md r5): unused auxiliary outputs (op Softmax
            # slots, norm statistics) kept whole [B, T, V]-scale
            # forward+backward chains alive.
            if emit_engine is not None and \
                    emit_engine.slim_fw_keep is not None:
                # emit mode: the engine's keep-set additionally excludes
                # post-backward reads that are (re)written before the
                # read and names the forward never computes — fewer vjp
                # primal outputs means fewer dense zero cotangents
                fw_keep = set(emit_engine.slim_fw_keep)
            else:
                fw_keep = set(fetch_names) | set(writeback) | {loss_name}

                def _collect_reads(op_list):
                    for op_after in op_list:
                        fw_keep.update(op_after.input_names())
                        # control-flow bodies read outer vars directly
                        # from env (not through input slots) — recurse
                        # like _analyze does
                        sb = op_after.attrs.get('sub_block')
                        if sb is not None:
                            _collect_reads(program.block(sb).ops)

                _collect_reads(ops[bw_idx + 1:])

            def fw(d):
                env2 = dict(rest)
                env2.update(d)
                _exec_ops(ops[:bw_idx], 0, env2, ectx, program)
                # probe entries must cross the vjp boundary as primal
                # outputs — they are not in any static keep-set (their
                # names are allocated during this very trace)
                return {k: v for k, v in env2.items()
                        if k in fw_keep or (
                            forensic is not None and
                            k.startswith(ForensicProbes.PREFIX))}

            env_out, pullback = jax.vjp(fw, diff)
            if loss_name not in env_out:
                raise ValueError('loss var %s not produced before backward'
                                 % loss_name)
            ct = {k: (jnp.ones_like(v) if k == loss_name
                      else _zero_cotangent(v))
                  for k, v in env_out.items()}
            grads, = pullback(ct)
            if emit_engine is not None and \
                    emit_engine.slim_fw_keep is not None:
                # the slim keep-set drops pass-through names (params the
                # optimizer reads but the forward never writes) from the
                # vjp primal outputs; post-backward ops read them from
                # the original environment instead
                env = dict(env0)
                env.update(env_out)
            else:
                env = dict(env_out)
            for slot, names in bw_op.outputs.items():
                if slot == 'Grads':
                    for p, gname in zip(pnames, names):
                        env[gname] = grads[p]
                        if forensic is not None:
                            forensic.env = env
                            forensic.note(
                                bw_idx, _BACKWARD_OP, gname,
                                getattr(bw_op, 'source_loc', None),
                                env[gname])
                elif slot == 'LossGrad':
                    env[names[0]] = jnp.ones_like(env[loss_name])
            _exec_ops(ops[bw_idx + 1:], bw_idx + 1, env, ectx, program)

        fetches = []
        for n in fetch_names:
            if n not in env:
                raise ValueError('fetch var %s was never computed' % n)
            fetches.append(env[n])
        updates = {n: env[n] for n in writeback if n in env}
        if mesh is not None:
            # pin every annotated writeback layout (the shard pass's
            # ZeRO specs included) so donated state comes back in the
            # layout _gather_params expects — steady state skips the
            # re-shard device_put entirely
            from jax.sharding import NamedSharding
            sh = program._sharding
            for n in updates:
                ps = sh.get(n)
                if ps is not None:
                    updates[n] = jax.lax.with_sharding_constraint(
                        updates[n], NamedSharding(mesh, ps))
        probes = None
        if forensic is not None:
            vals = [env[n] for n in forensic.names() if n in env]
            probes = (jnp.stack(vals) if vals
                      else jnp.zeros((0, 3), jnp.float32))
        if not check_nan:
            if forensic is not None:
                return fetches, updates, probes
            return fetches, updates
        ok = jnp.asarray(True)
        for v in itertools.chain(fetches, updates.values()):
            if hasattr(v, 'dtype') and jnp.issubdtype(v.dtype,
                                                      jnp.inexact):
                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(v)))
        if forensic is not None:
            return fetches, updates, ok, probes
        return fetches, updates, ok

    if steps is None:
        run_fn = step_fn
    else:
        def run_fn(params, feeds, counter):
            # feeds arrive stacked [steps, ...]; params thread as carry.
            # The carry only needs `required` names: a persistable that is
            # write-only within one step is overwritten before any read,
            # so its start-of-step value never matters — its LAST value is
            # recovered from the stacked per-step outputs below.
            import jax.lax as lax
            step_ids = jnp.arange(steps, dtype=jnp.uint32)

            def body(carry, xs):
                feeds_i, i = xs
                if check_nan:
                    p, ok_all = carry
                else:
                    p = carry
                res = step_fn(p, feeds_i, counter + i)
                fetches_i, updates_i = res[0], res[1]
                new_p = {n: updates_i[n] for n in p}
                extra_i = {n: v for n, v in updates_i.items() if n not in p}
                if check_nan:
                    return ((new_p, jnp.logical_and(ok_all, res[2])),
                            (fetches_i, extra_i))
                return new_p, (fetches_i, extra_i)

            init = (params, jnp.asarray(True)) if check_nan else params
            carry_out, (fetches, extras) = lax.scan(
                body, init, (feeds, step_ids))
            final_p = carry_out[0] if check_nan else carry_out
            updates = dict(final_p)
            updates.update({n: v[-1] for n, v in extras.items()})
            if check_nan:
                return fetches, updates, carry_out[1]
            return fetches, updates

    jit_kwargs = {}
    if donate and writeback:
        jit_kwargs['donate_argnums'] = (0,)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = program._sharding

        def shard_of(name, default=P()):
            return NamedSharding(mesh, spec.get(name, default))
        # feeds default to batch-sharding over the 'data' axis if present
        feed_default = P('data') if 'data' in mesh.axis_names else P()
        if steps is None:
            feed_shardings = {n: shard_of(n, feed_default)
                              for n in feed_names}
        else:
            # stacked feeds put the step axis first: prepend an
            # unsharded dim so the in-scan batch sharding matches the
            # single-step mesh path exactly
            def stacked_shard(name):
                s = spec.get(name, feed_default)
                return NamedSharding(mesh, P(*((None,) + tuple(s))))
            feed_shardings = {n: stacked_shard(n) for n in feed_names}
        jit_kwargs['in_shardings'] = (
            {n: shard_of(n) for n in params_in},
            feed_shardings,
            NamedSharding(mesh, P()),
        )
    return jax.jit(run_fn, **jit_kwargs), params_in, writeback


def _feed_spec(v):
    """(shape, dtype-string) of one feed/param value — the unit both the
    in-process hot key and the disk fingerprint are built from."""
    return (tuple(np.shape(v)),
            str(getattr(v, 'dtype', type(v).__name__)))


class _ExecEntry(object):
    """One resolved executable: `call` is the AOT-compiled artifact (from
    an eager lower().compile() or deserialized from disk); `jit_fn` is the
    lazily-specializing fallback kept for the rare input-spec drift an AOT
    executable cannot absorb (e.g. a scope param swapped to a new dtype).
    The strong `program` ref pins id(program) against recycling while the
    entry lives.  `shard_targets` (mesh launches only) maps each param to
    the NamedSharding of the OPTIMIZED program — the shard pass rewrites
    specs (ZeRO state sharding) on the optimizer twin, and gathering
    against the raw program's specs would re-replicate every launch."""
    __slots__ = ('call', 'jit_fn', 'params_in', 'writeback', 'program',
                 'fingerprint', 'shard_targets')

    def __init__(self, call, jit_fn, params_in, writeback, program,
                 fingerprint, shard_targets=None):
        self.call = call
        self.jit_fn = jit_fn
        self.params_in = params_in
        self.writeback = writeback
        self.program = program
        self.fingerprint = fingerprint
        self.shard_targets = shard_targets


def _tail_split_enabled():
    return os.environ.get('PT_TAIL_SPLIT', '1') not in ('0', 'false',
                                                        'False')


class Executor(object):
    """Parity: reference executor.py Executor (run/close/feed/fetch API)."""

    def __init__(self, place=None, mesh=None, check_nan=None,
                 nan_poll=None):
        self.place = place if place is not None else TPUPlace(0)
        self.mesh = mesh
        # nan/inf debug guard (SURVEY §2.8; parity: the reference's global
        # FLAGS_check_nan_inf, which makes every op kernel assert finite
        # outputs).  Whole-block lowering has no per-op boundary, so the
        # check covers everything that leaves the executable — fetches and
        # written-back persistables — as ONE fused all-finite scalar
        # compiled into the step; the per-array naming pass runs only
        # when that flag trips.
        if check_nan is None:
            check_nan = os.environ.get('FLAGS_check_nan_inf', '') in (
                '1', 'true', 'True')
        self.check_nan = bool(check_nan)
        # verdict poll cadence: the fused ok scalar accumulates on device
        # (running AND) and is only READ every nan_poll steps — the read
        # is the host sync that made check_nan cost 4x (PERF.md).  1 (the
        # default without PT_ASYNC/PT_NAN_POLL) is the synchronous
        # per-launch read, bit-for-bit.  Not part of the compile key: the
        # executable computes the same verdict either way.
        self.nan_poll = _async.default_nan_poll() if nan_poll is None \
            else max(1, int(nan_poll))
        self._nan = _async.DeferredNanVerdict(self.nan_poll)
        # L1 of the two-tier compilation cache (core/compile_cache.py):
        # fingerprinted executables, LRU-bounded by PT_EXEC_CACHE_MAX —
        # the seed's dict grew one executable per signature forever
        self._cache = _cc.ExecutableLRU()
        self._run_counter = {}
        # RNG counters restored from a checkpoint before their base_key
        # exists (fresh process): consumed on the first run of a matching
        # (feed names, fetch names) signature — see set_rng_state
        self._pending_counters = {}
        self._shard_targets = {}
        # largest K ever launched per (program, fetch set): a smaller K
        # against the same program is a ragged tail, and run_steps routes
        # it through the single-step executable instead of lowering a
        # whole new scan (PT_TAIL_SPLIT=0 restores per-tail lowering)
        self._steps_seen = {}
        # telemetry span tags (ParallelExecutor sets mesh/shard info here)
        self._obs_tags = {}

    def close(self):
        self._cache.clear()
        self._shard_targets.clear()
        self._steps_seen.clear()
        self._nan.reset()

    # ---------------------------------------------- deferred nan verdict
    def nan_clean(self):
        """True when no launch verdicts are pending an unread deferred
        poll — i.e. checkpointing NOW cannot capture state a later poll
        will condemn.  Always True with check_nan off or nan_poll=1
        (every launch polls before returning)."""
        return not self.check_nan or self._nan.pending_steps == 0

    def poll_nan(self):
        """Force the deferred verdict poll NOW (end of epoch/stream, or
        before an aligned checkpoint).  Raises the standard check_nan
        RuntimeError — with ``nan_window_steps`` attached — if any launch
        since the last poll produced non-finite values.  No-op when
        check_nan is off or nothing is pending."""
        if not self.check_nan:
            return
        window = self._nan.poll()
        if window:
            e = RuntimeError(_async.DEFERRED_TRIP_MSG % window)
            e.nan_window_steps = window
            e.nan_window_start = self._nan.last_window_start
            raise e

    def reset_nan_window(self):
        """Drop pending verdicts without reading them.  Recovery calls
        this after a rollback: verdicts accumulated over the poisoned
        stream say nothing about the restored state."""
        self._nan.reset()

    # ------------------------------------------------------- rng/run state
    @staticmethod
    def _stream_key(feed_names, fetch_names):
        return '|'.join(sorted(feed_names)) + '=>' + '|'.join(fetch_names)

    def rng_state(self):
        """JSON-able RNG/run-counter state, keyed program-agnostically by
        (feed names, fetch names) — id(program) and scope serials don't
        survive a process restart, the launch *signature* does.  The
        checkpointer saves this so a resumed run derives the exact
        per-step RNG keys (dropout masks included) the uninterrupted run
        would have: the counter fold-in makes the stream a pure function
        of (program seed, counter)."""
        out = {}
        for (pid, ver, feeds, fetch, sserial), v in \
                self._run_counter.items():
            k = self._stream_key(feeds, fetch)
            out[k] = max(int(v), out.get(k, 0))
        # carry still-unconsumed restored counters through re-checkpoints
        for k, v in self._pending_counters.items():
            out.setdefault(k, int(v))
        return out

    def set_rng_state(self, state):
        """Restore counters captured by `rng_state`.  Live base_keys with
        a matching signature are overwritten in place (in-process
        rollback); unseen signatures are parked and consumed on their
        first run (fresh-process resume).  A live stream ABSENT from the
        snapshot had not run when the checkpoint was taken — it rewinds
        to 0, so a rollback to a pre-stream checkpoint replays the exact
        counters (dropout masks, fault windows) the original run drew."""
        state = {k: int(v) for k, v in (state or {}).items()}
        consumed = set()
        for key in list(self._run_counter):
            k = self._stream_key(key[2], key[3])
            if k in state:
                self._run_counter[key] = state[k]
                consumed.add(k)
            else:
                self._run_counter[key] = 0
        self._pending_counters = {k: v for k, v in state.items()
                                  if k not in consumed}

    def stream_counter(self, feed_names, fetch_names):
        """The NEXT run counter a launch with this (feed names, fetch
        names) signature would consume.  Forensic replay (train/
        forensics.py) uses this right after a checkpoint restore to
        re-derive the exact per-step RNG keys the condemned window used."""
        k = self._stream_key(tuple(feed_names), tuple(fetch_names))
        best = None
        for key, v in self._run_counter.items():
            if self._stream_key(key[2], key[3]) == k:
                best = int(v) if best is None else max(best, int(v))
        if best is None:
            best = int(self._pending_counters.get(k, 0))
        return best

    def _resolve_fetch(self, fetch_list):
        names = []
        for f in _as_list(fetch_list):
            if isinstance(f, Variable):
                names.append(f.name)
            elif isinstance(f, str):
                names.append(f)
            else:
                raise TypeError('bad fetch entry: %r' % (f,))
        return names

    def _normalize_feed(self, block, feed):
        """One per-step feed dict -> {name: array}, with LoDTensor feeds
        expanded to padded+lengths and lod lengths synthesized for dense
        arrays fed into lod vars."""
        feed_vals = {}
        for k, v in (feed or {}).items():
            if not block.has_var(k):
                raise KeyError(
                    'feed var "%s" is not a variable of this program; '
                    'data vars: %s' % (k, sorted(
                        n for n, var in block.vars.items() if var.is_data)))
            from .lod import LoDTensor
            if isinstance(v, LoDTensor):
                feed_vals[k] = v.padded
                feed_vals[k + '@LENGTH'] = v.lengths
                if v.outer_lengths is not None and \
                        block.has_var(k + '@OUTERLEN'):
                    feed_vals[k + '@OUTERLEN'] = v.outer_lengths
            elif hasattr(v, 'devices'):
                # already a device array: pass through zero-copy (a feed
                # uploaded once with jax.device_put is NOT round-tripped
                # through the host every step)
                feed_vals[k] = v
            else:
                feed_vals[k] = np.asarray(v)
        # lod vars fed as plain dense arrays: synthesize full lengths
        for k in list(feed_vals.keys()):
            lname = k + '@LENGTH'
            if block.has_var(k) and block.var(k).lod_level > 0 and \
                    lname not in feed_vals and block.has_var(lname):
                arr = feed_vals[k]
                feed_vals[lname] = np.full((arr.shape[0],), arr.shape[1],
                                           dtype=np.int32)
        return feed_vals

    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name='feed', fetch_var_name='fetch', scope=None,
            return_numpy=True, use_program_cache=True, as_futures=False):
        """``as_futures=True`` is the non-blocking fetch mode: the call
        returns ``async_runtime.FetchFuture`` handles instead of arrays,
        so the host never waits on the device — sync happens lazily at
        ``.numpy()`` (metered in ``executor.host_blocked_s``).  The
        launch itself is identical; ``return_numpy`` is ignored."""
        if program is None:
            program = default_main_program()
        if isinstance(program, _CompiledProgramBase):
            return program._run(self, feed, fetch_list, scope, return_numpy,
                                as_futures=as_futures)
        scope = scope if scope is not None else global_scope()
        feed_vals = self._normalize_feed(program.global_block(), feed)
        return self._run_impl(program, feed_vals, fetch_list, scope,
                              return_numpy, use_program_cache, steps=None,
                              as_futures=as_futures)

    def run_steps(self, program=None, feed_list=None, fetch_list=None,
                  steps=None, scope=None, return_numpy=True,
                  use_program_cache=True, as_futures=False):
        """Run `steps` training iterations in ONE device launch.

        The K iterations lower to a single jitted lax.scan (see _lower):
        one dispatch through the device tunnel instead of K, donated
        state threaded through the scan carry, per-step RNG folded from
        the shared run counter — bitwise-identical on CPU to K
        sequential `run` calls with the same feeds.

        feed_list: a list of K per-step feed dicts, or ONE dict whose
        arrays are already stacked on a leading [K] axis (pass `steps`
        explicitly in that case — e.g. a superbatch from
        data_feeder.FeedPrefetcher).
        Returns the fetches stacked per step: each entry is [K, ...]
        (FetchFuture handles over the stacked device arrays when
        ``as_futures=True`` — consecutive launches then chain on-device
        with zero host round-trips between them).
        """
        if program is None:
            program = default_main_program()
        if isinstance(program, _CompiledProgramBase):
            return program._run_steps(self, feed_list, fetch_list, steps,
                                      scope, return_numpy,
                                      as_futures=as_futures)
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        if isinstance(feed_list, dict):
            if steps is None:
                raise ValueError(
                    'run_steps with a pre-stacked feed dict needs steps=K')
            feed_vals = {k: (v if hasattr(v, 'devices') else np.asarray(v))
                         for k, v in feed_list.items()}
            for k, v in feed_vals.items():
                if v.shape[0] != steps:
                    raise ValueError(
                        'stacked feed "%s" has leading dim %d, expected '
                        'steps=%d' % (k, v.shape[0], steps))
        else:
            per_step = [self._normalize_feed(block, f)
                        for f in (feed_list or [])]
            if not per_step:
                raise ValueError('run_steps needs a non-empty feed_list')
            if steps is None:
                steps = len(per_step)
            elif steps != len(per_step):
                raise ValueError('steps=%d but feed_list has %d entries'
                                 % (steps, len(per_step)))
            names = set(per_step[0])
            for f in per_step[1:]:
                if set(f) != names:
                    raise ValueError('per-step feeds disagree on keys: '
                                     '%s vs %s' % (sorted(names), sorted(f)))
            feed_vals = _stack_feeds(per_step)
        steps = int(steps)
        fetch_names = tuple(self._resolve_fetch(fetch_list))
        seen_key = (id(program), program._version, fetch_names)
        kmax = self._steps_seen.get(seen_key, 0)
        if (use_program_cache and _tail_split_enabled() and steps < kmax
                and self._hot_key(program, feed_vals, fetch_names, steps)
                not in self._cache):
            # ragged tail: a K smaller than this program has already
            # launched, with no executable for it.  Lowering a steps=K'
            # scan per distinct tail length is one full compile each;
            # K' launches of the (reused-forever) single-step executable
            # consume the same RNG counters and are bitwise identical.
            return self._run_tail_split(program, feed_vals, fetch_list,
                                        steps, scope, return_numpy,
                                        as_futures)
        self._steps_seen[seen_key] = max(kmax, steps)
        return self._run_impl(program, feed_vals, fetch_list, scope,
                              return_numpy, use_program_cache,
                              steps=steps, as_futures=as_futures)

    def _run_tail_split(self, program, feed_vals, fetch_list, steps, scope,
                        return_numpy, as_futures=False):
        """Run a ragged-tail superbatch as `steps` single-step launches.
        Output shape contract matches the fused path: fetches stacked on a
        leading [steps] axis.  The stack happens ON DEVICE — the per-step
        launches pipeline asynchronously and the host only syncs once at
        the end (return_numpy), or never (as_futures)."""
        if _obs.enabled():
            _obs.metrics.counter('executor.tail_splits').inc()
            _obs.instant('executor.tail_split', cat='compile',
                         args={'steps': steps})
        outs = [self._run_impl(program,
                               {k: v[i] for k, v in feed_vals.items()},
                               fetch_list, scope, False, True, steps=None)
                for i in range(steps)]
        import jax.numpy as jnp
        stacked = [jnp.stack([o[j] for o in outs])
                   for j in range(len(outs[0]))]
        if as_futures:
            return [_async.FetchFuture(s) for s in stacked]
        if return_numpy:
            with _async.host_block('tail_split_sync',
                                   extra_counter='executor.fetch_sync_s',
                                   steps=steps):
                return [np.asarray(s) for s in stacked]
        return stacked

    def _hot_key(self, program, feed_vals, fetch_names, steps):
        """In-process (L1) cache key.  Unlike the seed's key it includes
        feed shapes/dtypes — an entry holds one AOT-compiled executable,
        which (by design) has no lazy re-specialization to hide behind —
        and excludes the scope: the executable is scope-agnostic, state
        flows through its arguments."""
        return (id(program), program._version,
                tuple((n,) + _feed_spec(feed_vals[n])
                      for n in sorted(feed_vals)),
                fetch_names, self.check_nan, steps,
                _passes.config_token(), _emit.config_token(),
                _kg_token())

    def _shard_targets_for(self, program, params_in):
        """Param -> NamedSharding targets from `program._sharding`.
        Called with the OPTIMIZED program at entry-resolution time so the
        shard pass's rewritten specs (ZeRO accumulator/param sharding)
        are what the scope arrays get device_put to."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = program._sharding
        return {n: NamedSharding(self.mesh, spec.get(n, P()))
                for n in params_in}

    def _gather_params(self, program, params_in, scope, base_key,
                       targets=None):
        import jax
        import jax.numpy as jnp
        params = {}
        for n in params_in:
            if n not in scope:
                raise RuntimeError(
                    'persistable var "%s" not initialized in scope — run the '
                    'startup program first (exe.run(startup_program))' % n)
            v = scope.vars[n]
            if not hasattr(v, 'devices'):
                # host (numpy) array in scope — a checkpoint restore,
                # load_persistables, or manual scope.set.  It must be
                # uploaded into an XLA-OWNED buffer before it meets a
                # donating executable: on the CPU backend device_put can
                # zero-copy ALIAS the numpy memory, and donating that
                # buffer frees memory numpy still owns — observed as
                # glibc heap corruption in resume-after-restore training.
                # jnp.array forces the copy; written back so the upload
                # happens once per restore, not once per launch.
                v = jnp.array(np.asarray(v))
                scope.vars[n] = v
            params[n] = v
        if self.mesh is not None:
            # arrays in scope may carry a different (e.g. replicated)
            # committed sharding from the startup run; reshard to the
            # program's annotated layout.  Target shardings are cached per
            # lowering entry, and device_put is skipped once the written-
            # back arrays already carry the right sharding (steady state).
            if targets is None:
                targets = self._shard_targets.get(base_key)
            if targets is None:
                targets = self._shard_targets_for(program, params_in)
                self._shard_targets[base_key] = targets
            params = {n: (v if getattr(v, 'sharding', None) == targets[n]
                          else jax.device_put(v, targets[n]))
                      for n, v in params.items()}
        return params

    def _resolve_entry(self, program, feed_vals, feed_names, fetch_names,
                       scope, steps, base_key, counter, use_cache, obs_on):
        """Two-tier executable resolution (see core/compile_cache.py):
        L1 in-process LRU by hot key; on miss, the canonical fingerprint
        is tried against the disk store (a hit skips trace AND compile);
        on a disk miss the program is traced and AOT-compiled eagerly
        (`jit(fn).lower(...).compile()`) and the executable serialized
        back to disk for the next process."""
        hot_key = (self._hot_key(program, feed_vals, fetch_names, steps)
                   if use_cache else None)
        if use_cache:
            entry = self._cache.get(hot_key)
            if entry is not None:
                return entry, self._gather_params(
                    program, entry.params_in, scope, base_key,
                    targets=entry.shard_targets)
        # PT_LINT gate on the RAW program, BEFORE the rewriter: a user's
        # def-use/shape bug must be named here, not DCE'd out of sight
        from ..analysis import apply_lint_policy, lint_mode
        apply_lint_policy(program, feed_names=feed_names,
                          fetch_names=fetch_names, mode=lint_mode(),
                          header='program lint failed before lowering')
        # Program->Program rewriter (core/passes): the tracer sees the
        # optimized twin; every cache key/RNG stream stays keyed on the
        # RAW program (PT_OPT toggling is part of the hot key + launch
        # signature via config_token, so it reads as a named change)
        t_o0 = time.perf_counter() if obs_on else None
        opt_program, opt_stats = _passes.maybe_optimize(program, fetch_names)
        if obs_on and opt_stats is not None:
            _obs.tracing.add_span(
                'executor.optimize', t_o0, time.perf_counter(),
                cat='compile',
                args=dict(self._obs_tags,
                          raw=opt_stats['op_count_raw'],
                          opt=opt_stats['op_count_opt']) or None)
        # Direct Program->jaxpr emitter (core/emit): built on the
        # optimized twin so emission sees the fused/rng_stream-stamped
        # shape.  A static coverage gap falls back PER PROGRAM to the
        # traced path — loudly (emitter.fallbacks counters, warn-once,
        # PT_STRICT_EMIT=1 raises naming the op).  The cache-bypass path
        # (use_cache=False) keeps seed semantics and never emits.
        engine, emit_verdict = None, 'trace'
        if use_cache and _emit.enabled():
            try:
                engine = _emit.build_engine(opt_program, feed_names,
                                            fetch_names)
                emit_verdict = 'emit'
            except _emit.EmitFallback as e:
                if _emit.strict():
                    raise
                _emit.note_fallback(e.op, e.why)
                emit_verdict = 'emit_fallback:%s' % e.op
        t_l0 = time.perf_counter() if obs_on else None
        jit_fn, params_in, writeback = _lower(
            opt_program, feed_names, fetch_names, donate=True,
            mesh=self.mesh, check_nan=self.check_nan, steps=steps,
            emit_engine=engine)
        if obs_on:
            _obs.metrics.counter('executor.lowerings').inc()
            _obs.tracing.add_span(
                'executor.lower', t_l0, time.perf_counter(), cat='compile',
                args=dict(self._obs_tags, steps=steps) or None)
        shard_targets = self._shard_targets_for(opt_program, params_in)
        params = self._gather_params(program, params_in, scope, base_key,
                                     targets=shard_targets)
        if not use_cache:
            # cache bypass keeps the seed semantics: a lazily-retracing
            # jit call per run, observed by the explainer at call time
            return (_ExecEntry(jit_fn, jit_fn, params_in, writeback,
                               program, None, shard_targets), params)

        call, fp, disk_tier = None, None, None
        if _cc.disk_enabled():
            _cc.ensure_xla_cache_backstop()
            # fingerprint the OPTIMIZED desc: it is what actually lowers,
            # and it folds the PT_OPT config in for free (PT_OPT=0 hashes
            # the raw desc, a skipped pass changes the rewrite output)
            # emit-mode entries carry the emitter version + coverage set
            # in the key; fallback (and PT_EMIT=0) entries use extra=None
            # so traced artifacts are SHARED across modes on disk.
            # kernelgen (when on) composes its version + rule coverage
            # into the extra on BOTH modes — generated kernels change
            # what lowers on the traced path too
            fp = _cc.launch_fingerprint(
                opt_program,
                {n: _feed_spec(feed_vals[n]) for n in feed_names},
                fetch_names, steps, self.check_nan, mesh=self.mesh,
                param_specs={n: _feed_spec(v) for n, v in params.items()},
                extra=_compose_fp_extra(
                    engine.fingerprint_extra() if engine is not None
                    else None))
            t_a0 = time.perf_counter()
            call, disk_tier = _cc.disk_cache().load(fp)
            if obs_on:
                t_a1 = time.perf_counter()
                if call is not None:
                    _obs.metrics.counter('compile_cache.disk_hits').inc()
                    _obs.metrics.counter('compile_cache.load_s').inc(
                        t_a1 - t_a0)
                    _obs.tracing.add_span(
                        'executor.aot_load', t_a0, t_a1, cat='compile',
                        args=dict(self._obs_tags, steps=steps) or None)
                    sig = _launch_signature(program, feed_vals, feed_names,
                                            fetch_names, steps,
                                            self.check_nan, scope)
                    _obs.explainer().observe_disk_load(
                        sig, load_s=t_a1 - t_a0)
                else:
                    _obs.metrics.counter('compile_cache.disk_misses').inc()
        if call is None:
            tc0 = _TRACE_COUNT[0]
            args = (params, {n: feed_vals[n] for n in feed_names},
                    np.uint32(counter & 0xffffffff))
            t_c0 = time.perf_counter()
            try:
                traced = jit_fn.trace(*args)
            except _emit.EmitError as e:
                # runtime emission gap (e.g. an op outside the known RNG
                # set drew ctx.rng): rebuild this program on the traced
                # path.  The fingerprint is recomputed with extra=None so
                # the stored artifact is the shared traced one.
                if engine is None or _emit.strict():
                    raise
                _emit.note_fallback(e.op, e.why)
                emit_verdict = 'emit_fallback:%s' % e.op
                engine = None
                jit_fn, params_in, writeback = _lower(
                    opt_program, feed_names, fetch_names, donate=True,
                    mesh=self.mesh, check_nan=self.check_nan,
                    steps=steps)
                if fp is not None:
                    fp = _cc.launch_fingerprint(
                        opt_program,
                        {n: _feed_spec(feed_vals[n]) for n in feed_names},
                        fetch_names, steps, self.check_nan,
                        mesh=self.mesh,
                        param_specs={n: _feed_spec(v)
                                     for n, v in params.items()},
                        extra=_compose_fp_extra(None))
                traced = jit_fn.trace(*args)
            t_cmid = time.perf_counter()
            lowered = traced.lower()
            call = lowered.compile()
            t_c1 = time.perf_counter()
            # emit_s: wall time inside the emitter (memo build +
            # dispatch); trace_s: the residual jaxpr-staging time.  With
            # the staged AOT API the StableHLO lowering now lands in
            # backend_compile_s for BOTH modes (accounting change vs
            # PR-5, documented in PERF.md).
            emit_s = engine.take_build_seconds() if engine is not None \
                else 0.0
            if obs_on:
                _obs.metrics.counter('executor.emit_s').inc(emit_s)
                _obs.metrics.counter('executor.trace_s').inc(
                    max(0.0, (t_cmid - t_c0) - emit_s))
                _obs.metrics.counter('executor.backend_compile_s').inc(
                    t_c1 - t_cmid)
            if obs_on and _TRACE_COUNT[0] > tc0:
                sig = _launch_signature(program, feed_vals, feed_names,
                                        fetch_names, steps, self.check_nan,
                                        scope)
                cache_status = ('disabled' if fp is None else
                                'stablehlo_hit' if disk_tier == 'stablehlo'
                                else 'miss')
                report = _obs.explainer().observe(
                    sig, compile_s=t_c1 - t_c0, cache=cache_status,
                    lowering=emit_verdict)
                _obs.tracing.add_span(
                    'executor.trace_compile', t_c0, t_c1, cat='compile',
                    args=dict(self._obs_tags, steps=steps,
                              kind=report['kind'],
                              lowering=emit_verdict,
                              cause='; '.join(report['details'])[:512]
                              or None))
            if fp is not None:
                t_s0 = time.perf_counter()
                tier = _cc.disk_cache().store(
                    fp, compiled=call, lowered=lowered,
                    meta={'steps': steps, 'fetch': list(fetch_names),
                          'program': _cc.program_fingerprint(opt_program)})
                if tier and obs_on:
                    _obs.metrics.counter('compile_cache.store_s').inc(
                        time.perf_counter() - t_s0)
        entry = _ExecEntry(call, jit_fn, params_in, writeback, program, fp,
                           shard_targets)
        self._cache.put(hot_key, entry)
        return entry, params

    def prepare(self, program=None, feed=None, fetch_list=None, scope=None,
                steps=None):
        """AOT pre-warm: resolve — load from disk, or trace+compile and
        persist — the executable for the given feed signature WITHOUT
        running a step.  `feed` maps name -> example array or a
        ``(shape, dtype)`` spec (zeros are synthesized); ``steps=K``
        pre-warms the fused K-step scan (the example feeds are stacked
        internally).  The scope must already hold initialized persistables
        (run the startup program first).  Returns the entry's disk
        fingerprint, or None when the disk tier is disabled."""
        if program is None:
            program = default_main_program()
        scope = scope if scope is not None else global_scope()
        example = {}
        for k, v in (feed or {}).items():
            if isinstance(v, tuple) and len(v) == 2 and \
                    not hasattr(v, 'dtype'):
                from .dtypes import convert_dtype
                shape, dtype = v
                v = np.zeros(tuple(int(d) for d in shape),
                             convert_dtype(dtype))
            example[k] = v
        feed_vals = self._normalize_feed(program.global_block(), example)
        if steps is not None:
            steps = int(steps)
            feed_vals = _stack_feeds([feed_vals] * steps)
        feed_names = tuple(sorted(feed_vals.keys()))
        fetch_names = tuple(self._resolve_fetch(fetch_list))
        base_key = (id(program), program._version, feed_names, fetch_names,
                    scope._serial)
        entry, _ = self._resolve_entry(
            program, feed_vals, feed_names, fetch_names, scope, steps,
            base_key, 0, True, _obs.enabled())
        if steps is not None:
            seen_key = (id(program), program._version, fetch_names)
            self._steps_seen[seen_key] = max(
                self._steps_seen.get(seen_key, 0), steps)
        return entry.fingerprint

    def _run_impl(self, program, feed_vals, fetch_list, scope,
                  return_numpy, use_program_cache, steps,
                  as_futures=False):
        feed_names = tuple(sorted(feed_vals.keys()))
        fetch_names = tuple(self._resolve_fetch(fetch_list))

        # telemetry: ONE flag check per launch; when off, the hot path
        # below does no telemetry work (no spans, no counters, no dicts)
        obs_on = _obs.enabled()
        if obs_on:
            _obs.on_launch_start(self, time.perf_counter())

        # rng/shard-layout bookkeeping stays scope-local (unlike the
        # executable): parallel scopes keep independent RNG streams.
        # The stream is keyed WITHOUT check_nan or steps: toggling the
        # debug flag mid-training does not restart dropout masks, and a
        # K-step launch consumes the same K counters that K sequential
        # runs would — mixed run/run_steps usage shares one stream
        base_key = (id(program), program._version, feed_names, fetch_names,
                    scope._serial)
        counter = self._run_counter.get(base_key)
        if counter is None:
            # first launch of this signature: a checkpoint-restored
            # counter (set_rng_state) resumes the stream mid-sequence
            counter = int(self._pending_counters.pop(
                self._stream_key(feed_names, fetch_names), 0)) \
                if self._pending_counters else 0
        if _faults.any_active():
            # preemption rehearsal: SIGTERM delivered as step `at` is
            # ABOUT TO launch — before the counter bump and writeback, so
            # the signal handler's flushed checkpoint sees scope, RNG
            # counters, and caller-recorded progress all consistent at
            # "step at-1 complete"
            _faults.maybe_kill('sigterm', step=counter, count=steps or 1)
        self._run_counter[base_key] = counter + (steps or 1)

        if _faults.any_active():
            # nan_step fault site: poison this launch's float feeds so
            # the fused check_nan verdict trips like a real divergence
            feed_vals = _faults.poison_nan(feed_vals, counter, steps or 1)

        entry, params = self._resolve_entry(
            program, feed_vals, feed_names, fetch_names, scope, steps,
            base_key, counter, use_program_cache, obs_on)

        if obs_on:
            tc0 = _TRACE_COUNT[0]
            t_d0 = time.perf_counter()
        feeds = {n: feed_vals[n] for n in feed_names}
        ctr = np.uint32(counter & 0xffffffff)
        try:
            result = entry.call(params, feeds, ctr)
        except TypeError:
            # an input spec drifted under an AOT executable (scope param
            # swapped to a new dtype/sharding): the artifact cannot
            # re-specialize, so drop this entry to the lazily-retracing
            # jit fallback — the explainer names the retrace below
            if entry.call is entry.jit_fn:
                raise
            entry.call = entry.jit_fn
            result = entry.call(params, feeds, ctr)
        if obs_on:
            t_d1 = time.perf_counter()
            _obs.metrics.counter('executor.launches').inc()
            if _TRACE_COUNT[0] > tc0:
                # only the jit-fallback / cache-bypass paths trace at call
                # time; cached-path traces happen inside _resolve_entry
                sig = _launch_signature(program, feed_vals, feed_names,
                                        fetch_names, steps, self.check_nan,
                                        scope)
                report = _obs.explainer().observe(sig, compile_s=t_d1 - t_d0)
                _obs.tracing.add_span(
                    'executor.trace_compile', t_d0, t_d1, cat='compile',
                    args=dict(self._obs_tags, steps=steps,
                              kind=report['kind'],
                              cause='; '.join(report['details'])[:512]
                              or None))
            else:
                _obs.tracing.add_span(
                    'executor.dispatch', t_d0, t_d1, cat='launch',
                    args=dict(self._obs_tags, steps=steps) or None)
        fetches, updates = result[0], result[1]
        # write back BEFORE the nan check: params were donated, so the old
        # scope arrays are dead — raising first would leave the scope
        # holding deleted buffers right when the user wants to inspect it
        for n, v in updates.items():
            scope.vars[n] = v
        if self.check_nan:
            # the fused verdict stays device-resident: push accumulates
            # it into a running AND (async, no host read) and only a DUE
            # window forces the one host sync.  nan_poll=1 makes every
            # launch due — bit-for-bit the old per-launch bool(ok) read.
            self._nan.push(result[2], steps or 1, start=counter)
            if self._nan.due():
                window = self._nan.poll()
                if window:
                    # tripped: per-array pass to NAME the culprits (slow,
                    # but only runs on actual failure).  For a K-step
                    # launch the fetches are stacked [K, ...] and the
                    # updates are end-of-scan state — both still name the
                    # vars; a deferred window's culprit usually persists
                    # into them (NaN propagates through params).  The
                    # launch window must CLOSE before the raise: otherwise
                    # the next launch (after a divergence rollback)
                    # measures its gap from the launch before this one and
                    # reads the whole failed step + recovery as a phantom
                    # pipeline stall.
                    try:
                        self._raise_non_finite(fetch_names, fetches,
                                               updates, window)
                    finally:
                        if obs_on:
                            _obs.on_launch_end(self, time.perf_counter())
        if as_futures:
            # non-blocking fetch mode: hand back device handles; the sync
            # (if any) happens at FetchFuture.numpy(), where it is metered
            fetches = [_async.FetchFuture(f) for f in fetches]
        elif return_numpy:
            # the host-sync point of the launch: converting fetches blocks
            # on the device — its duration is how long the async pipeline
            # made the host wait (near-zero in steady state)
            t_f0 = time.perf_counter() if obs_on else None
            fetches = [np.asarray(f) for f in fetches]
            if obs_on:
                t_f1 = time.perf_counter()
                _obs.metrics.counter('executor.fetch_sync_s').inc(
                    t_f1 - t_f0)
                _obs.metrics.counter('executor.host_blocked_s').inc(
                    t_f1 - t_f0)
                _obs.metrics.histogram('executor.fetch_sync_ms').observe(
                    (t_f1 - t_f0) * 1000.0)
                _obs.tracing.add_span('executor.fetch_sync', t_f0, t_f1,
                                      cat='launch')
        if obs_on:
            # drop the donated input refs NOW, inside the launch window: on
            # the CPU backend freeing a donated buffer blocks until its
            # consuming execution completes, and at frame teardown that
            # wait would land AFTER the end mark — misread as inter-launch
            # host gap (phantom pipeline stalls).  On TPU the free is async
            # and this is instant.
            t_w0 = time.perf_counter()
            params = None  # noqa: F841 - the free IS the point
            t_w1 = time.perf_counter()
            if t_w1 - t_w0 > 1e-4:
                _obs.tracing.add_span('executor.donate_wait', t_w0, t_w1,
                                      cat='launch')
            _obs.memory.on_launch()
            _obs.on_launch_end(self, t_w1)
        return fetches

    def _raise_non_finite(self, fetch_names, fetches, updates, window):
        """A (possibly deferred) verdict poll tripped: name the culprits
        still visible in the latest launch's arrays, annotating the raise
        with the window size; if the non-finite values no longer show
        there (possible when the window spans launches), raise the
        deferred-window message instead.  nan_poll=1 keeps today's exact
        behavior: the naming pass over this launch's own arrays."""
        try:
            self._assert_finite(itertools.chain(
                zip(fetch_names, fetches), updates.items()))
        except RuntimeError as e:
            e.nan_window_steps = window
            e.nan_window_start = self._nan.last_window_start
            raise
        if window > 1:
            e = RuntimeError(_async.DEFERRED_TRIP_MSG % window)
            e.nan_window_steps = window
            e.nan_window_start = self._nan.last_window_start
            raise e

    @staticmethod
    def _assert_finite(named_arrays):
        import jax.numpy as jnp
        named = []
        flags = []
        for n, v in named_arrays:
            try:
                flags.append(jnp.all(jnp.isfinite(v)))   # async dispatch
                named.append(n)
            except TypeError:
                continue  # non-numeric (e.g. tensor arrays) — skip
        if not flags:
            return
        # ONE host sync for the fused verdict — per-array host round
        # trips made check_nan >30x slower through the tunnel (PERF.md);
        # the naming pass below only runs on failure
        ok = flags[0]
        for f in flags[1:]:
            ok = jnp.logical_and(ok, f)
        if bool(ok):
            return
        bad = [n for n, f in zip(named, flags) if not bool(f)]
        raise RuntimeError(
            'check_nan: non-finite values (nan/inf) detected after this '
            'step in: %s. Typical causes: exploding gradients (try '
            'gradient clipping or a lower LR), log/div of zero, or '
            'uninitialized feeds.' % ', '.join(sorted(bad)))


class _CompiledProgramBase(object):
    """Marker base so Executor.run can dispatch CompiledProgram wrappers
    (see compiler.py / parallel/parallel_executor.py)."""

    def _run(self, exe, feed, fetch_list, scope, return_numpy,
             as_futures=False):
        raise NotImplementedError

    def _run_steps(self, exe, feed_list, fetch_list, steps, scope,
                   return_numpy, as_futures=False):
        raise NotImplementedError
