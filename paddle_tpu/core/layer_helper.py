"""LayerHelper — shared plumbing for all layer functions.

Parity: reference python/paddle/fluid/layer_helper.py (create_parameter with
ParamAttr + default initializer, bias/activation helpers, dtype inference).
"""
from . import framework
from .framework import default_main_program, default_startup_program
from . import unique_name
from ..param_attr import ParamAttr

__all__ = ['LayerHelper']


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get('name', None)
        if name is None:
            self.kwargs['name'] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs['name']

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    def multiple_input(self, input_param_name='input'):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, (list, tuple)):
            return list(inputs)
        return [inputs]

    def input(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" %
                             self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('param_attr', None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('bias_attr', None))

    def multiple_param_attr(self, length):
        param_attr = self.param_attr
        if isinstance(param_attr, ParamAttr):
            param_attr = [param_attr]
        if len(param_attr) != 1 and len(param_attr) != length:
            raise ValueError('parameter number mismatch')
        elif len(param_attr) == 1 and length != 1:
            import copy
            param_attr = [copy.deepcopy(param_attr[0])
                          for _ in range(length)]
        return param_attr

    def input_dtype(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError('data types of inputs must be consistent')
        return dtype

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        from ..initializer import Xavier, Constant
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            default_initializer = Constant(0.0) if is_bias else Xavier()
        attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate('.'.join(
                [self.kwargs['name'], 'b' if is_bias else 'w']))
        shape = [int(d) for d in shape]
        param = self.block.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())
        if framework._imperative[0] is not None and \
                param._ivalue is not None:
            return param  # eager reuse: already initialized on a prior call
        attr.initializer(param)
        return param

    def create_variable_for_type_inference(self, dtype=None, stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate('.'.join([self.name, 'tmp'])),
            dtype=dtype, stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.block.create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def create_or_get_global_variable(self, name, *args, **kwargs):
        gb = self.main_program.global_block()
        if name in gb.vars:
            return gb.vars[name]
        return self.create_global_variable(*args, name=name, **kwargs)

    def set_variable_initializer(self, var, initializer):
        initializer(var)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None and \
                'bias_attr' in self.kwargs and self.kwargs['bias_attr'] is False:
            return input_var
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        if b is None:
            return input_var
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(type='elementwise_add',
                       inputs={'X': input_var, 'Y': b},
                       outputs={'Out': tmp},
                       attrs={'axis': dim_start})
        return tmp

    def append_activation(self, input_var, act=None):
        act = act if act is not None else self.kwargs.get('act', None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act_type, act_attrs = act, {}
        else:
            act = dict(act)
            act_type = act.pop('type')
            act_attrs = act
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(type=act_type, inputs={'X': input_var},
                       outputs={'Out': tmp}, attrs=act_attrs)
        return tmp
