"""Build-time def-use ordering validation.

Parity: reference ParallelExecutor's SSA-graph dependency tracking
(paddle/fluid/framework/details/*_ssa_graph_*.cc) exists to detect races
between concurrently scheduled op kernels.  Under whole-block XLA
lowering ops execute in program order inside ONE executable, so a "race"
can only appear as a def-use ordering bug: an op reading a var no
earlier op, feed, parameter, or persistable defines.

The walk itself now lives in paddle_tpu/analysis/passes/defuse.py as the
D001 lint pass (one engine serves Program.lint(), tools/pt_lint.py, and
the executor's PT_LINT hook); this module keeps the historical
first-error ValueError contract on top of it, with the upgraded
diagnostics: full block path and a did-you-mean suggestion for the
nearest var name by edit distance.
"""

__all__ = ['validate_def_use']


def validate_def_use(program, feed_names=()):
    """Raise ValueError on the first op input read before definition."""
    from ..analysis import lint_program, LintError, LintResult
    result = lint_program(program, feed_names=feed_names,
                          passes=('def_use',))
    errors = [d for d in result.errors if d.code == 'D001']
    if errors:
        # first-error contract: historical callers matched one violation
        raise LintError(LintResult(errors[:1]),
                        header='def-use violation')
