"""Build-time def-use ordering validation.

Parity: reference ParallelExecutor's SSA-graph dependency tracking
(paddle/fluid/framework/details/*_ssa_graph_*.cc) exists to detect races
between concurrently scheduled op kernels.  Under whole-block XLA
lowering ops execute in program order inside ONE executable, so a "race"
can only appear as a def-use ordering bug: an op reading a var no
earlier op, feed, parameter, or persistable defines.  The executor runs
this walk on every lowering-cache miss so such programs fail at build
with the op and var named, instead of a bare KeyError mid-trace.
"""
from .framework import Parameter

__all__ = ['validate_def_use']


def _initially_defined(program, feed_names):
    defined = set(feed_names)
    root = program.global_block()
    for name, v in root.vars.items():
        if isinstance(v, Parameter) or v.persistable or \
                getattr(v, 'is_data', False):
            defined.add(name)
            if getattr(v, 'lod_level', 0) > 0:
                defined.add(name + '@LENGTH')
    return defined


def validate_def_use(program, feed_names=()):
    """Raise ValueError on the first op input read before definition."""

    def walk(block, defined):
        for op in block.ops:
            for slot, names in op.inputs.items():
                for n in names:
                    if n is None or n in defined:
                        continue
                    v = block._find_var_recursive(n)
                    if v is not None and (isinstance(v, Parameter) or
                                          v.persistable or
                                          getattr(v, 'is_data', False) or
                                          # arrays allocate on first
                                          # write; the runtime raises its
                                          # own read-before-write error
                                          getattr(v, 'is_tensor_array',
                                                  False)):
                        defined.add(n)
                        continue
                    raise ValueError(
                        'def-use violation: op "%s" reads var "%s" '
                        'before any prior op, feed, parameter or '
                        'persistable defines it (block %d). If this var '
                        'is produced later in the program, reorder the '
                        'ops; if it should be fed, add it to the feed '
                        'list.' % (op.type, n, block.idx))
            sub = op.attrs.get('sub_block')
            if sub is not None:
                inner = set(defined)
                if op.type == 'recurrent':
                    inner |= set(op.attrs.get('step_vars', ()))
                    inner |= set(op.attrs.get('mem_vars', ()))
                # body-LOCAL temps do NOT survive the loop: the lowering
                # writes back only carries (vars that pre-existed), so
                # sub-block definitions are deliberately not merged — a
                # later read of a body temp is itself a def-use violation
                walk(program.block(sub), inner)
            defined.update(n for n in op.output_names() if n)
        return defined

    walk(program.global_block(),
         _initially_defined(program, feed_names))
