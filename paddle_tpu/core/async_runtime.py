"""Fully-async executor runtime: never let the host serialize the device.

JAX dispatches launches asynchronously — the device only waits on the
host when the host *reads* (``np.asarray``, ``bool()``, a blocking
fetch).  PERF.md measured the cost of ignoring that: the per-launch
``check_nan`` verdict read alone held a 4x slowdown, because one
``bool(ok)`` per step drains the whole dispatch pipeline.

This module holds the three primitives the executor's async mode is
built from:

  * ``host_block(reason)`` — a context manager that meters every forced
    host<->device sync into the ``executor.host_blocked_s`` counter and
    a ``host_block`` span, so "how much did the host serialize the
    device" is a recorded number, not a vibe.
  * ``FetchFuture`` — the handle ``run``/``run_steps`` return in
    non-blocking mode (``as_futures=True``): the device array plus a
    lazy, cached, metered ``.numpy()``.
  * ``DeferredNanVerdict`` — the fused all-finite verdict stays
    device-resident as a running AND across launches and is only read
    (one host sync) every ``poll_every`` steps.  ``PT_NAN_POLL=1`` — the
    default unless ``PT_ASYNC=1`` opts in — reproduces the synchronous
    per-launch read bit-for-bit.

Env knobs (see docs/async.md):

  ``PT_ASYNC=1``     opt the process into async defaults (deferred
                     verdict polling every ``_ASYNC_DEFAULT_POLL`` steps).
  ``PT_NAN_POLL=N``  explicit verdict poll cadence in steps; overrides
                     the PT_ASYNC default.  N=1 is today's synchronous
                     semantics.
"""
import contextlib
import os
import time

import numpy as np

from .. import observability as _obs

__all__ = ['FetchFuture', 'DeferredNanVerdict', 'host_block',
           'async_enabled', 'default_nan_poll', 'DEFERRED_TRIP_MSG']

# deferred-poll cadence when PT_ASYNC=1 and PT_NAN_POLL is unset: long
# enough to amortize the verdict read over a fused launch window, short
# enough that a rollback replays a bounded number of steps
_ASYNC_DEFAULT_POLL = 8

# a deferred trip cannot always name a single step: the running AND only
# says "some step since the last poll went non-finite".  The message MUST
# keep the 'check_nan' prefix — train/recovery.py classifies divergences
# by it.
DEFERRED_TRIP_MSG = (
    'check_nan: non-finite values (nan/inf) detected by a deferred '
    'verdict poll covering the last %d step(s) — the divergence is '
    'localized to this window, not a single step (set PT_NAN_POLL=1 '
    'for per-step attribution). Roll back to a checkpoint saved before '
    'the window (Executor.nan_clean() aligned saves guarantee one).')


def async_enabled():
    return os.environ.get('PT_ASYNC', '') in ('1', 'true', 'True')


def default_nan_poll():
    """Verdict poll cadence: explicit ``PT_NAN_POLL`` wins; otherwise 1
    (the synchronous per-launch read, bit-for-bit today's semantics)
    unless ``PT_ASYNC=1`` opts the process into deferred polling."""
    env = os.environ.get('PT_NAN_POLL', '')
    if env:
        return max(1, int(env))
    return _ASYNC_DEFAULT_POLL if async_enabled() else 1


@contextlib.contextmanager
def host_block(reason, extra_counter=None, **args):
    """Meter a forced host<->device sync.

    Every second spent inside lands in ``executor.host_blocked_s`` (plus
    ``extra_counter`` when a site keeps a legacy per-site counter) and a
    ``host_block`` span tagged with the reason — verdict polls, future
    reads, checkpoint snapshots all become visible, attributable time."""
    if not _obs.enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        _obs.metrics.counter('executor.host_blocked_s').inc(t1 - t0)
        if extra_counter:
            _obs.metrics.counter(extra_counter).inc(t1 - t0)
        _obs.tracing.add_span('host_block', t0, t1, cat='launch',
                              args=dict(args, reason=reason))


class FetchFuture(object):
    """One not-yet-synced fetch from a non-blocking run (``as_futures``).

    Wraps the device array; nothing blocks until the caller asks for host
    data.  ``numpy()`` (and the ``np.asarray(fut)`` protocol) forces the
    sync ONCE, meters it via ``host_block``, and caches the host copy.
    ``__getitem__`` returns a still-lazy future over a device-side slice,
    so a stacked ``[K, ...]`` fetch hands out per-step views for free."""
    __slots__ = ('_device', '_host', '_reason')

    def __init__(self, device_value, reason='fetch_future'):
        self._device = device_value
        self._host = None
        self._reason = reason

    def device(self):
        """The underlying device array — never blocks."""
        return self._device

    @property
    def shape(self):
        return tuple(self._device.shape)

    @property
    def dtype(self):
        return self._device.dtype

    def ready(self):
        """True once the producing computation finished (non-blocking)."""
        if self._host is not None:
            return True
        is_ready = getattr(self._device, 'is_ready', None)
        return bool(is_ready()) if callable(is_ready) else True

    def block(self):
        """Wait for the device value WITHOUT copying it to host."""
        if self._host is None:
            bur = getattr(self._device, 'block_until_ready', None)
            if callable(bur):
                with host_block(self._reason):
                    bur()
        return self

    def numpy(self):
        if self._host is None:
            with host_block(self._reason):
                self._host = np.asarray(self._device)
        return self._host

    def __array__(self, dtype=None):
        a = self.numpy()
        return a if dtype is None else a.astype(dtype, copy=False)

    def __float__(self):
        return float(self.numpy())

    def __getitem__(self, idx):
        return FetchFuture(self._device[idx], reason=self._reason)

    def __len__(self):
        return int(self._device.shape[0])

    def __repr__(self):
        return '<FetchFuture %s %s %s>' % (
            self.shape, self.dtype,
            'synced' if self._host is not None else 'pending')


class DeferredNanVerdict(object):
    """Device-resident running AND of per-launch all-finite verdicts.

    ``push`` accumulates each launch's fused ``ok`` scalar with a device
    ``logical_and`` (async, never blocks); ``poll`` performs the ONE host
    sync per window and resets it.  With ``poll_every=1`` every push is
    immediately due, reproducing the synchronous per-launch read."""
    __slots__ = ('poll_every', '_ok', '_pending', '_start',
                 'last_window_start')

    def __init__(self, poll_every=1):
        self.poll_every = max(1, int(poll_every))
        self._ok = None
        self._pending = 0
        self._start = None           # run counter of the window's first step
        self.last_window_start = None  # ... of the last polled window

    @property
    def pending_steps(self):
        """Steps since the last poll — the rollback window a trip at the
        next poll would condemn (exported as the ``nan_poll.lag_steps``
        gauge)."""
        return self._pending

    def push(self, ok, steps=1, start=None):
        """``start`` is the run counter of the launch's first step — kept
        so a trip can tell forensics exactly which window to replay."""
        if self._ok is None:
            self._ok = ok
            if start is not None:
                self._start = int(start)
        else:
            import jax.numpy as jnp
            self._ok = jnp.logical_and(self._ok, ok)
        self._pending += int(steps)
        if _obs.enabled():
            _obs.metrics.gauge('nan_poll.lag_steps').set(self._pending)

    def due(self):
        return self._pending >= self.poll_every

    def poll(self):
        """Force the host sync on the accumulated verdict.  Returns 0
        when clean (or nothing pending), else the number of steps the
        tripped window covers.  The window resets either way — after a
        rollback the next window starts clean."""
        if self._ok is None:
            return 0
        window = self._pending
        with host_block('nan_poll', steps=window):
            ok = bool(self._ok)
        self.last_window_start = self._start
        self._ok = None
        self._pending = 0
        self._start = None
        if _obs.enabled():
            _obs.metrics.counter('nan_poll.polls').inc()
            _obs.metrics.gauge('nan_poll.lag_steps').set(0)
            if not ok:
                _obs.metrics.counter('nan_poll.trips').inc()
        return 0 if ok else window

    def reset(self):
        """Drop pending verdicts without reading them — the rollback
        path: verdicts computed on the pre-restore stream say nothing
        about the restored state."""
        if self._pending and _obs.enabled():
            _obs.metrics.counter('nan_poll.window_resets').inc()
        self._ok = None
        self._pending = 0
        self._start = None
        if _obs.enabled():
            _obs.metrics.gauge('nan_poll.lag_steps').set(0)
