"""LoDTensor: variable-length sequence batches, TPU-native.

Parity: reference paddle/fluid/framework/lod_tensor.{h,cc} and
python/paddle/fluid/lod_tensor.py.  The reference stores ragged rows
contiguously with a CPU-side level-of-detail offset table; that layout forces
dynamic shapes, which XLA cannot tile onto the MXU.  Here a LoDTensor is a
dense padded array `[batch, max_len, ...]` plus an int32 `lengths[batch]`
vector.  Sequence ops (layers/sequence.py) consume (data, lengths) and use
masks / segment ids — static shapes, fully fusable.

When a LoDTensor is fed to `Executor.run`, the executor feeds `<name>` with
the padded data and `<name>@LENGTH` with the lengths (see core/executor.py).
"""
import numpy as np

__all__ = ['LoDTensor', 'create_lod_tensor', 'create_random_int_lodtensor',
           'LENGTH_SUFFIX']

LENGTH_SUFFIX = '@LENGTH'


class LoDTensor(object):
    def __init__(self, padded, lengths):
        self.padded = np.asarray(padded)
        self.lengths = np.asarray(lengths, dtype=np.int32)
        assert self.padded.ndim >= 2, 'LoDTensor padded data needs [B, T, ...]'
        assert self.lengths.shape == (self.padded.shape[0],)

    @property
    def shape(self):
        return self.padded.shape

    @property
    def dtype(self):
        return self.padded.dtype

    def recursive_sequence_lengths(self):
        return [self.lengths.tolist()]

    def lod(self):
        return [np.concatenate([[0], np.cumsum(self.lengths)]).tolist()]

    def rows(self):
        """Back to a python list of per-sequence arrays."""
        return [self.padded[i, :l] for i, l in enumerate(self.lengths)]

    def flatten_rows(self):
        """Reference-style packed [sum(lens), ...] layout (for numpy-side
        comparisons in tests)."""
        return np.concatenate(self.rows(), axis=0) if len(self.lengths) else \
            self.padded[:0, 0]

    def __repr__(self):
        return 'LoDTensor(shape=%s, lengths=%s)' % (
            self.padded.shape, self.lengths.tolist())


def create_lod_tensor(data, recursive_seq_lens=None, place=None,
                      max_len=None):
    """Build a LoDTensor.  `data` may be:
    - a list of per-sequence numpy arrays / lists (ragged), or
    - a packed [sum(lens), ...] array with recursive_seq_lens=[[l0, l1, ...]]
      (the reference calling convention, lod_tensor.py:create_lod_tensor).
    """
    if isinstance(data, LoDTensor):
        return data
    if isinstance(data, (list, tuple)) and recursive_seq_lens is None:
        rows = [np.asarray(r) for r in data]
        rows = [r.reshape(len(r), -1) if r.ndim == 1 else r for r in rows]
    else:
        arr = np.asarray(data)
        lens = list(recursive_seq_lens[-1])
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        assert offsets[-1] == arr.shape[0], (
            'sum of seq lens %d != rows %d' % (offsets[-1], arr.shape[0]))
        rows = [arr[offsets[i]:offsets[i + 1]] for i in range(len(lens))]
    lengths = np.array([len(r) for r in rows], dtype=np.int32)
    T = int(max_len or (lengths.max() if len(lengths) else 1))
    T = max(T, 1)
    feat = rows[0].shape[1:] if rows else (1,)
    dtype = rows[0].dtype if rows else np.float32
    padded = np.zeros((len(rows), T) + tuple(feat), dtype=dtype)
    for i, r in enumerate(rows):
        padded[i, :len(r)] = r
    return LoDTensor(padded, lengths)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    lens = recursive_seq_lens[-1]
    total = int(np.sum(lens))
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape)).astype(np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
