"""LoDTensor: variable-length sequence batches, TPU-native.

Parity: reference paddle/fluid/framework/lod_tensor.{h,cc} and
python/paddle/fluid/lod_tensor.py.  The reference stores ragged rows
contiguously with a CPU-side level-of-detail offset table; that layout forces
dynamic shapes, which XLA cannot tile onto the MXU.  Here a LoDTensor is a
dense padded array `[batch, max_len, ...]` plus an int32 `lengths[batch]`
vector.  Sequence ops (layers/sequence.py) consume (data, lengths) and use
masks / segment ids — static shapes, fully fusable.

When a LoDTensor is fed to `Executor.run`, the executor feeds `<name>` with
the padded data and `<name>@LENGTH` with the lengths (see core/executor.py).
"""
import numpy as np

__all__ = ['LoDTensor', 'create_lod_tensor', 'create_random_int_lodtensor',
           'LENGTH_SUFFIX', 'OUTER_SUFFIX']

LENGTH_SUFFIX = '@LENGTH'
# 2-level LoD companion: number of inner sequences per outer group
OUTER_SUFFIX = '@OUTERLEN'


class LoDTensor(object):
    """Padded+lengths LoD.  lod_level=1: padded [B, T, ...] with
    lengths[B].  lod_level=2 (nested, reference lod_tensor.py:24-76):
    the batch dim enumerates the INNER sequences and `outer_lengths[G]`
    is the lengths-of-lengths companion — group g owns inner rows
    sum(outer[:g]) : sum(outer[:g+1]).  The reference's recursive
    offset tables map to (outer_lengths, lengths) exactly."""

    def __init__(self, padded, lengths, outer_lengths=None):
        self.padded = np.asarray(padded)
        self.lengths = np.asarray(lengths, dtype=np.int32)
        assert self.padded.ndim >= 2, 'LoDTensor padded data needs [B, T, ...]'
        assert self.lengths.shape == (self.padded.shape[0],)
        self.outer_lengths = None
        if outer_lengths is not None:
            self.outer_lengths = np.asarray(outer_lengths, dtype=np.int32)
            assert self.outer_lengths.sum() == self.padded.shape[0], (
                'outer lengths %s must cover all %d inner sequences'
                % (self.outer_lengths.tolist(), self.padded.shape[0]))

    @property
    def shape(self):
        return self.padded.shape

    @property
    def dtype(self):
        return self.padded.dtype

    @property
    def lod_level(self):
        return 2 if self.outer_lengths is not None else 1

    def recursive_sequence_lengths(self):
        if self.outer_lengths is not None:
            return [self.outer_lengths.tolist(), self.lengths.tolist()]
        return [self.lengths.tolist()]

    def lod(self):
        """Reference offset-based LoD ([[0, ...]] per level)."""
        inner = np.concatenate([[0], np.cumsum(self.lengths)]).tolist()
        if self.outer_lengths is None:
            return [inner]
        outer = np.concatenate(
            [[0], np.cumsum(self.outer_lengths)]).tolist()
        return [outer, inner]

    def rows(self):
        """Back to a python list of per-sequence arrays."""
        return [self.padded[i, :l] for i, l in enumerate(self.lengths)]

    def nested_rows(self):
        """lod_level=2 view: list (outer groups) of lists of arrays."""
        assert self.outer_lengths is not None, 'not a 2-level LoDTensor'
        flat = self.rows()
        out, i = [], 0
        for g in self.outer_lengths:
            out.append(flat[i:i + g])
            i += g
        return out

    def flatten_rows(self):
        """Reference-style packed [sum(lens), ...] layout (for numpy-side
        comparisons in tests)."""
        return np.concatenate(self.rows(), axis=0) if len(self.lengths) else \
            self.padded[:0, 0]

    def to_packed(self):
        """(packed [sum(lens), ...] array, recursive_seq_lens) in the
        reference calling convention — the loud converter boundary for
        code that wants the contiguous layout back."""
        return np.asarray(self.flatten_rows()), \
            self.recursive_sequence_lengths()

    def __repr__(self):
        if self.outer_lengths is not None:
            return 'LoDTensor(shape=%s, outer=%s, lengths=%s)' % (
                self.padded.shape, self.outer_lengths.tolist(),
                self.lengths.tolist())
        return 'LoDTensor(shape=%s, lengths=%s)' % (
            self.padded.shape, self.lengths.tolist())


def create_lod_tensor(data, recursive_seq_lens=None, place=None,
                      max_len=None):
    """Build a LoDTensor.  `data` may be:
    - a list of per-sequence numpy arrays / lists (ragged), or
    - a nested list of lists of sequences (2-level), or
    - a packed [sum(lens), ...] array with
      recursive_seq_lens=[[l0, l1, ...]] (1-level) or
      [[g0, g1, ...], [l0, l1, ...]] (2-level) — the reference calling
      convention (lod_tensor.py:create_lod_tensor, 2-level examples in
      its docstrings).
    """
    if isinstance(data, LoDTensor):
        return data
    outer = None
    if isinstance(data, (list, tuple)) and recursive_seq_lens is None:
        # 1-level ragged rows; 2-level list input must state its
        # grouping via recursive_seq_lens (the reference asserts the
        # same — list shape alone is ambiguous)
        rows = [np.asarray(r) for r in data]
        rows = [r.reshape(len(r), -1) if r.ndim == 1 else r for r in rows]
    elif isinstance(data, (list, tuple)) and \
            len(recursive_seq_lens) == 2 and data and \
            isinstance(data[0], (list, tuple)) and \
            np.asarray(data[0][0]).ndim >= 1:
        # nested list (groups of sequences) + explicit 2-level lens
        outer = np.asarray(recursive_seq_lens[0], dtype=np.int32)
        assert [len(g) for g in data] == outer.tolist(), (
            'data grouping and recursive_seq_lens[0] do not match')
        rows = [np.asarray(r) for g in data for r in g]
        assert [len(r) for r in rows] == list(recursive_seq_lens[1]), (
            'data and recursive_seq_lens[1] do not match')
        rows = [r.reshape(len(r), -1) if r.ndim == 1 else r for r in rows]
    else:
        if isinstance(data, (list, tuple)):
            # reference list convention: flat list of sequences,
            # concatenated then re-split by recursive_seq_lens (the
            # reference reshapes word-id rows to [n, 1] the same way)
            arr = np.concatenate(
                [np.asarray(s).reshape(len(s), -1) for s in data], axis=0)
        else:
            arr = np.asarray(data)
        lens = list(recursive_seq_lens[-1])
        if len(recursive_seq_lens) == 2:
            outer = np.asarray(recursive_seq_lens[0], dtype=np.int32)
            assert outer.sum() == len(lens), (
                'level-0 lengths %s must cover the %d level-1 sequences'
                % (outer.tolist(), len(lens)))
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        assert offsets[-1] == arr.shape[0], (
            'sum of seq lens %d != rows %d' % (offsets[-1], arr.shape[0]))
        rows = [arr[offsets[i]:offsets[i + 1]] for i in range(len(lens))]
    lengths = np.array([len(r) for r in rows], dtype=np.int32)
    T = int(max_len or (lengths.max() if len(lengths) else 1))
    T = max(T, 1)
    feat = rows[0].shape[1:] if rows else (1,)
    dtype = rows[0].dtype if rows else np.float32
    padded = np.zeros((len(rows), T) + tuple(feat), dtype=dtype)
    for i, r in enumerate(rows):
        padded[i, :len(r)] = r
    return LoDTensor(padded, lengths, outer_lengths=outer)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    lens = recursive_seq_lens[-1]
    total = int(np.sum(lens))
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape)).astype(np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
