"""Reader decorators (parity: reference python/paddle/reader/decorator.py)."""
import itertools
import random
from queue import Queue
from threading import Thread

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'firstn', 'xmap_readers', 'multiprocess_reader', 'cache']


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b
    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            for e in r():
                yield e
    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                yield sum((make_tuple(o) for o in outputs if o is not None),
                          ())
    return reader


def buffered(reader, size):
    class EndSignal(object):
        pass
    end = EndSignal()

    def read_worker(r, q):
        # a worker that dies silently would leave the consumer blocked on
        # q.get() forever — carry the exception across and re-raise it
        try:
            for d in r:
                q.put(d)
            q.put(end)
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            q.put(e)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            if isinstance(e, BaseException):
                raise e
            yield e
            e = q.get()
    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    # thread-pool mapper (the reference uses threads too)
    def data_reader():
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(process_num) as pool:
            it = reader()
            pending = []
            for sample in it:
                pending.append(pool.submit(mapper, sample))
                if len(pending) >= buffer_size:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()
    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    # single-process fallback: chain (zero-egress sandboxed env)
    return chain(*readers)


def cache(reader):
    all_data = []

    def __impl__():
        if not all_data:
            for d in reader():
                all_data.append(d)
        for d in all_data:
            yield d
    return __impl__
