"""Inference engine: load a saved inference model into a standalone,
jit-compiled predictor, with optional AOT serialization.

Parity: reference paddle/fluid/inference/ (analysis passes, predictor C-API,
api_impl.cc NativePredictor / AnalysisPredictor).  TPU-native redesign: the
reference runs IR analysis passes (fusion, BN folding, TensorRT subgraphs)
over the program then interprets it per-op; here the whole pruned program is
lowered to ONE XLA executable — XLA *is* the analysis/fusion pass — and can be
exported ahead-of-time as serialized StableHLO via jax.export.
"""
import os
import threading

import numpy as np

from . import io as fluid_io
from . import observability as _obs
from .core import compile_cache as _cc
from .core.executor import Executor, Scope, _feed_spec, _lower, scope_guard

__all__ = ['AnalysisConfig', 'Predictor', 'create_paddle_predictor',
           'export_serialized', 'load_serialized']


class AnalysisConfig(object):
    """Thin config (parity: reference AnalysisConfig / NativeConfig).
    GPU/MKLDNN/TensorRT toggles are accepted and ignored — XLA on TPU
    replaces all of them."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self.use_bf16 = False
        self._ignored = {}

    def enable_bf16(self):
        self.use_bf16 = True

    # accepted-for-compat no-ops (XLA handles fusion/placement)
    def enable_use_gpu(self, *a, **k):
        self._ignored['use_gpu'] = a

    def disable_gpu(self):
        pass

    def enable_mkldnn(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        self._ignored['tensorrt'] = a

    def switch_ir_optim(self, flag=True):
        pass


class Predictor(object):
    """Self-contained inference runner: own Scope + one cached XLA
    executable per feed-shape signature."""

    def __init__(self, config):
        if isinstance(config, str):
            config = AnalysisConfig(config)
        self._config = config
        self._scope = Scope()
        self._exe = Executor()
        with scope_guard(self._scope):
            program, feed_names, fetch_vars = fluid_io.load_inference_model(
                config.model_dir, self._exe,
                model_filename=config.prog_file,
                params_filename=config.params_file)
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = [v.name for v in fetch_vars]
        if config.use_bf16:
            self._cast_params_bf16()
        # PT_OPT rewriter (core/passes): serving traces the optimized
        # twin too; lint policy stays anchored on the raw program, which
        # _lower checks when the rewriter is disabled
        from .core import passes as _passes
        if _passes.enabled():
            from .analysis import apply_lint_policy, lint_mode
            apply_lint_policy(self._program,
                              feed_names=tuple(self._feed_names),
                              fetch_names=tuple(self._fetch_names),
                              mode=lint_mode(),
                              header='program lint failed before lowering')
        opt_program, _ = _passes.maybe_optimize(
            self._program, tuple(self._fetch_names))
        # one lowering; the jitted fn re-specializes per feed shape itself
        self._fn, self._params_in, _ = _lower(
            opt_program, tuple(self._feed_names),
            tuple(self._fetch_names), donate=False)
        # per-shape AOT executables, warm-started from the persistent
        # cache (core/compile_cache.py) when PT_CACHE is on: a freshly
        # started serving process skips trace AND compile for every feed
        # shape it has ever seen on this machine.  Concurrent predicts
        # (the serving engine's dispatch thread + direct callers) share
        # the dict under a lock with single-flight per shape: the first
        # thread to see a cold shape compiles, the rest wait for its
        # result instead of duplicating a multi-second compile.
        self._compiled = {}
        self._compile_lock = threading.Lock()
        self._inflight = {}   # shape_key -> Event set when compile ends

    def _cast_params_bf16(self):
        import jax.numpy as jnp
        for name, val in list(self._scope.vars.items()):
            if hasattr(val, 'dtype') and val.dtype == jnp.float32:
                self._scope.vars[name] = val.astype(jnp.bfloat16)

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def lint(self, bucketer=None):
        """Static analysis of the loaded inference program (the saved
        model's feed/fetch signature anchors the def-use and dead-op
        passes).  Returns a paddle_tpu.analysis.LintResult; the same
        report is available from the CLI as
        ``python tools/pt_lint.py <model_dir>``."""
        return self._program.lint(feed_names=self._feed_names,
                                  fetch_list=self._fetch_names,
                                  bucketer=bucketer)

    def _fn_for(self, feeds):
        if not _cc.disk_enabled():
            return self._fn, self._params_in
        shape_key = tuple((n,) + _feed_spec(feeds[n]) for n in sorted(feeds))
        while True:
            with self._compile_lock:
                call = self._compiled.get(shape_key)
                if call is not None:
                    return call, self._params_in
                ev = self._inflight.get(shape_key)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[shape_key] = ev
                    break   # this thread owns the compile
            # another thread is compiling this shape: wait, then re-check
            # (its failure leaves the cache cold; the retry compiles here)
            _obs.metrics.counter('predictor.single_flight_waits').inc()
            ev.wait()
        try:
            call = self._compile_shape(shape_key, feeds)
            return call, self._params_in
        finally:
            with self._compile_lock:
                self._inflight.pop(shape_key, None)
            ev.set()

    def _compile_shape(self, shape_key, feeds):
        _cc.ensure_xla_cache_backstop()
        params = {n: self._scope.vars[n] for n in self._params_in}
        fp = _cc.launch_fingerprint(
            self._program, {n: _feed_spec(v) for n, v in feeds.items()},
            tuple(self._fetch_names), None, False,
            param_specs={n: _feed_spec(v) for n, v in params.items()},
            extra='predictor')
        call, _tier = _cc.disk_cache().load(fp)
        if call is None:
            _obs.metrics.counter('compile_cache.disk_misses').inc()
            lowered = self._fn.lower(params, dict(feeds), np.uint32(0))
            call = lowered.compile()
            _cc.disk_cache().store(fp, compiled=call, lowered=lowered,
                                   meta={'kind': 'predictor'})
        else:
            _obs.metrics.counter('compile_cache.disk_hits').inc()
        with self._compile_lock:
            self._compiled[shape_key] = call
        return call

    def run(self, feeds):
        """feeds: dict name->array, or list of arrays in input-name order.
        Returns list of numpy arrays in output-name order."""
        if isinstance(feeds, (list, tuple)):
            feeds = dict(zip(self._feed_names, feeds))
        import jax.numpy as jnp
        feeds = {n: jnp.asarray(v) for n, v in feeds.items()}
        fn, params_in = self._fn_for(feeds)
        params = {n: self._scope.vars[n] for n in params_in}
        fetches, _ = fn(params, feeds, np.uint32(0))
        return [np.asarray(f) for f in fetches]

    __call__ = run


def create_paddle_predictor(config):
    """Parity: reference paddle::CreatePaddlePredictor."""
    return Predictor(config)


# ------------------------------------------------------- AOT export

def export_serialized(predictor, example_feeds, path):
    """AOT-lower the predictor on example feeds and serialize the whole
    XLA computation (StableHLO bytes via jax.export) + params to `path`.
    The artifact runs without the program/ops — deploy-time parity with the
    reference's exported inference binaries."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    if isinstance(example_feeds, (list, tuple)):
        example_feeds = dict(zip(predictor._feed_names, example_feeds))
    example_feeds = {n: jnp.asarray(v) for n, v in example_feeds.items()}
    # export must trace, so it uses the jit fn — an AOT Compiled from
    # _fn_for cannot be called with tracers
    fn, params_in = predictor._fn, predictor._params_in
    params = {n: predictor._scope.vars[n] for n in params_in}

    def infer(params, feeds):
        fetches, _ = fn(params, feeds, np.uint32(0))
        return tuple(fetches)

    exported = jax_export.export(jax.jit(infer))(params, example_feeds)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, 'computation.bin'), 'wb') as f:
        f.write(exported.serialize())
    np.savez(os.path.join(path, 'params.npz'),
             **{n: np.asarray(v) for n, v in params.items()})
    with open(os.path.join(path, 'signature.txt'), 'w') as f:
        f.write('\n'.join(predictor._feed_names) + '\n--\n' +
                '\n'.join(predictor._fetch_names))
    return path


def load_serialized(path):
    """Load an AOT artifact; returns fn(feeds: dict) -> list[np.ndarray]."""
    import jax.numpy as jnp
    from jax import export as jax_export

    with open(os.path.join(path, 'computation.bin'), 'rb') as f:
        exported = jax_export.deserialize(f.read())
    data = np.load(os.path.join(path, 'params.npz'))
    params = {n: jnp.asarray(data[n]) for n in data.files}
    with open(os.path.join(path, 'signature.txt')) as f:
        feed_part = f.read().split('\n--\n')[0]
    feed_names = [n for n in feed_part.split('\n') if n]

    def run(feeds):
        if isinstance(feeds, (list, tuple)):
            feeds = dict(zip(feed_names, feeds))
        feeds = {n: jnp.asarray(v) for n, v in feeds.items()}
        out = exported.call(params, feeds)
        return [np.asarray(o) for o in out]

    return run
