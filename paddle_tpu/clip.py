"""Gradient / error clipping.

Parity: reference python/paddle/fluid/clip.py (ErrorClipByValue,
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
set_gradient_clip).
"""
from .core.framework import op_role_guard, OpRole

__all__ = ['ErrorClipByValue', 'GradientClipByValue', 'GradientClipByNorm',
           'GradientClipByGlobalNorm', 'set_gradient_clip',
           'append_gradient_clip_ops']


class BaseErrorClipAttr(object):
    pass


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class BaseGradientClipAttr(object):
    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _create_operators(self, param, grad):
        block = grad.block
        block.append_op(type='clip', inputs={'X': grad},
                        outputs={'Out': grad},
                        attrs={'min': self.min, 'max': self.max})
        return param, grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        block = grad.block
        block.append_op(type='clip_by_norm', inputs={'X': grad},
                        outputs={'Out': grad},
                        attrs={'max_norm': self.clip_norm})
        return param, grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        context.setdefault(self.group_name, []).append((param, grad, self))

    @staticmethod
    def _create_group_operators(group):
        from .layers import nn as nn_layers
        from .layers import tensor as tensor_layers
        from .layers import ops as ops_layers
        clip_norm = group[0][2].clip_norm
        sq_sums = []
        for p, g, _ in group:
            sq = ops_layers.square(g)
            sq_sums.append(nn_layers.reduce_sum(sq))
        global_sq = tensor_layers.sums(sq_sums)
        global_norm = ops_layers.sqrt(global_sq)
        cn = tensor_layers.fill_constant([1], 'float32', clip_norm)
        scale = cn / nn_layers.elementwise_max(global_norm, cn)
        out = []
        for p, g, _ in group:
            g.block.append_op(type='elementwise_mul',
                              inputs={'X': g, 'Y': scale},
                              outputs={'Out': g}, attrs={'axis': -1})
            out.append((p, g))
        return out


_clip_attr_of_program = {}


def set_gradient_clip(clip, param_list=None, program=None):
    from .core.framework import default_main_program
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [program.global_block().var(p) if isinstance(p, str) else p
                  for p in param_list]
    for p in param_list:
        p.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads):
    res = []
    context = {}
    with op_role_guard(OpRole.Backward):
        for p, g in param_grads:
            clip = getattr(p, 'gradient_clip_attr', None)
            if clip is None:
                res.append((p, g))
            elif isinstance(clip, GradientClipByGlobalNorm):
                clip._process_context(context, p, g)
            else:
                res.append(clip._create_operators(p, g))
        for group in context.values():
            res.extend(
                GradientClipByGlobalNorm._create_group_operators(group))
    return res
