"""WeightedAverage (parity: reference python/paddle/fluid/average.py).

Host-side running weighted mean over fetched batch metrics.
"""
import numpy as np

__all__ = ['WeightedAverage']


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or np.isscalar(x)


class WeightedAverage(object):
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError('value must be a number or ndarray')
        if not _is_number_or_matrix(weight):
            raise ValueError('weight must be a number or ndarray')
        value = np.mean(np.asarray(value, dtype='float64'))
        weight = float(np.sum(np.asarray(weight, dtype='float64')))
        if self.numerator is None:
            self.numerator = 0.0
            self.denominator = 0.0
        self.numerator += value * weight
        self.denominator += weight

    def eval(self):
        if not self.denominator:
            raise ValueError('nothing accumulated — call add() first')
        return self.numerator / self.denominator
