"""paddle_tpu.testing — fault injection and resilience test utilities.

The training runtime's failure paths (torn checkpoint writes, transient
cache I/O errors, NaN bursts, preemption signals, prefetcher stalls) are
impossible to exercise reliably without a way to *cause* them on demand.
`faults` provides deterministic, named injection sites driven by the
``PT_FAULT`` environment variable or the `configure()` API.
"""
from . import faults  # noqa: F401

__all__ = ['faults']
