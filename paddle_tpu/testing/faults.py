"""Deterministic fault injection for the training runtime.

A *fault site* is a named point in the runtime that asks this module
"should I fail right now?".  Sites are armed via the ``PT_FAULT``
environment variable (or `configure()`), one comma-separated entry per
site::

    PT_FAULT="ckpt_write:at=2,nan_step:at=5,prefetch_stall:at=1:s=0.2"

Each entry is ``site[:key=value]*`` with fields

  * ``at=N``     fire on the N-th invocation of the site (1-based), or —
                 for step-indexed sites like ``nan_step``/``sigterm`` —
                 at global step counter N (0-based, matching the
                 executor's RNG/run counter).
  * ``times=K``  keep firing for K consecutive invocations/steps
                 (default 1).
  * ``s=SEC``    sleep duration for stall-type sites (default 0.05).

Injection is deterministic: no randomness, no wall-clock dependence —
the same program with the same ``PT_FAULT`` fails the same way every
run, so failure-path tests are exactly as reproducible as happy-path
ones.  Every fired fault counts into the observability registry as
``faults.injected`` and ``faults.injected.<site>``.

Instrumented sites (kept in sync with docs/robustness.md):

  ===============  ====================================================
  ``ckpt_write``   checkpoint writer fails after the tensor file is on
                   disk but BEFORE the ``_SUCCESS`` marker — a torn
                   checkpoint (train/checkpoint.py)
  ``cache_read``   compile-cache disk read raises OSError
                   (core/compile_cache.py)
  ``cache_write``  compile-cache disk write raises OSError
  ``io_read``      io.load_vars tensor read raises OSError (io.py)
  ``io_write``     io.save_vars tensor write raises OSError
  ``nan_step``     one training step's float feeds are overwritten with
                   NaN — loss and gradients blow up and the executor's
                   fused check_nan verdict trips (core/executor.py).
                   ``row=R`` restricts the poison to batch row R so
                   forensic row bisection has a ground truth to find
  ``prefetch_stall``  the FeedPrefetcher worker sleeps ``s`` seconds
                   before packing a superbatch (data_feeder.py)
  ``feed_read``    one reader pull inside the FeedPrefetcher worker
                   raises OSError INSIDE the retried callable — a
                   transient reader blip that ``retry_with_backoff``
                   must absorb instead of killing the trainer
                   (data_feeder.py)
  ``sigterm``      the process sends itself SIGTERM after step N
                   completes (core/executor.py) — preemption rehearsal
  ``serve_dispatch``  the serving engine's batch dispatch raises —
                   every request in the batch gets an error reply and
                   the circuit breaker counts a failure
                   (serving/engine.py)
  ``serve_slow_batch``  the dispatch thread sleeps ``s`` seconds before
                   running a batch — a latency spike the p99 SLO sees
  ``queue_overflow``  one admission decision is forced to treat the
                   request queue as full, exercising the configured
                   overflow policy (reject / shed-oldest) on demand
  ``compile_storm``  a batch is treated as a cold-compile: the dispatch
                   thread sleeps ``s`` seconds and the breaker counts a
                   cold batch — enough consecutive ones trip it
  ``ckpt_io``      a single checkpoint disk write raises OSError INSIDE
                   the retried callable — unlike ``ckpt_write`` (a
                   simulated crash) this is a transient blip that
                   ``retry_with_backoff`` must absorb
                   (train/checkpoint.py)
  ``decode_step``  one fused decode window of the streaming generation
                   scheduler raises BEFORE the runtime is touched —
                   every decoding request gets an error reply, the KV
                   slots free, and the breaker counts a failure
                   (serving/generation/scheduler.py)
  ``kv_oom``       the paged KV pool reports exhaustion on one
                   allocation: at admission the request stays QUEUED
                   (backpressure); mid-stream the stream retires with
                   a terminal ``kv_oom`` reply and a flight dump
                   carrying the pool gauges — never a truncation
                   (serving/generation/kv_cache.py)
  ``device_loss``  a pod participant stops heartbeating at step ``at``
                   and hangs — peers must detect the loss and trip
                   recovery instead of waiting on a dead collective
                   (parallel/health.py)
  ``host_desync``  a participant's heartbeat (and its shard META) report
                   a step far ahead of the roster — the desync guard
                   must refuse to commit a mixed-step checkpoint
                   (parallel/health.py, train/checkpoint.py)
  ===============  ====================================================
"""
import os
import signal
import threading
import time

from .. import observability as _obs

__all__ = ['configure', 'reset', 'any_active', 'active', 'fire', 'fire_in',
           'maybe_fail', 'maybe_sleep', 'maybe_kill', 'poison_nan',
           'forensic_replay', 'spec', 'InjectedFault', 'SITES']

SITES = ('ckpt_write', 'ckpt_io', 'cache_read', 'cache_write', 'io_read',
         'io_write', 'nan_step', 'prefetch_stall', 'feed_read', 'sigterm',
         'serve_dispatch', 'serve_slow_batch', 'queue_overflow',
         'compile_storm', 'decode_step', 'device_loss', 'host_desync',
         'kv_oom')


class InjectedFault(OSError):
    """The exception maybe_fail raises — an OSError subclass so every
    transient-I/O handler (and retry_with_backoff) treats it exactly
    like a real disk failure."""


class _Fault(object):
    __slots__ = ('site', 'at', 'times', 'sleep_s', 'row', 'hits', 'fired')

    def __init__(self, site, at=1, times=1, s=0.05, row=None):
        self.site = site
        self.at = int(at)
        self.times = max(1, int(times))
        self.sleep_s = float(s)
        self.row = None if row is None else int(row)
        self.hits = 0       # invocation counter for hit-indexed sites
        self.fired = 0


_ACTIVE = {}
_CONFIGURED = [False]
_REPLAY = [0]          # >0: forensic replay — nan_step ignores its budget
_LOCK = threading.Lock()


def configure(text=None):
    """Arm fault sites from a PT_FAULT-style spec string (None re-reads
    the environment).  Replaces any previous configuration."""
    with _LOCK:
        _ACTIVE.clear()
        if text is None:
            text = os.environ.get('PT_FAULT', '')
        for part in (p.strip() for p in text.split(',')):
            if not part:
                continue
            fields = part.split(':')
            site = fields[0].strip()
            kw = {}
            for f in fields[1:]:
                k, _, v = f.partition('=')
                k = k.strip()
                if k not in ('at', 'times', 's', 'row'):
                    raise ValueError(
                        'PT_FAULT field %r for site %r not understood '
                        '(known: at=N, times=K, s=SEC, row=R)' % (k, site))
                kw[k] = float(v) if k == 's' else int(v)
            _ACTIVE[site] = _Fault(site, **kw)
        _CONFIGURED[0] = True
    return dict(_ACTIVE)


def reset():
    """Disarm everything and forget the cached env parse (the next site
    query re-reads PT_FAULT)."""
    with _LOCK:
        _ACTIVE.clear()
        _CONFIGURED[0] = False


def _ensure():
    if not _CONFIGURED[0]:
        configure()


def any_active():
    """One cheap check for hot paths: is ANY site armed?"""
    _ensure()
    return bool(_ACTIVE)


def active(site):
    _ensure()
    return site in _ACTIVE


def spec(site):
    """The armed _Fault for a site (None if disarmed).  Read-only use:
    tests and soak harnesses compare a forensic verdict against the
    injected ground truth (``spec('nan_step').at`` / ``.row``)."""
    _ensure()
    return _ACTIVE.get(site)


def _count(site):
    _obs.metrics.counter('faults.injected').inc()
    _obs.metrics.counter('faults.injected.%s' % site).inc()
    _obs.tracing.instant('fault.injected', cat='fault', args={'site': site})


def _replaying(site):
    # forensic replay re-runs already-fired steps to localize the poison:
    # nan_step must reproduce the original NaNs without consuming (or
    # being blocked by) the spent budget
    return _REPLAY[0] > 0 and site == 'nan_step'


def fire(site, step=None):
    """Deterministic fire decision.  ``step=None`` counts invocations of
    the site (1-based, fires on hits in [at, at+times)); an explicit
    ``step`` compares the caller's own index (e.g. the executor's run
    counter) against the armed window instead."""
    _ensure()
    spec = _ACTIVE.get(site)
    if spec is None:
        return False
    with _LOCK:
        replay = _replaying(site)
        if spec.fired >= spec.times and not replay:
            # budget spent: a rollback that rewinds the caller's step
            # counter must not re-fire the same fault forever
            return False
        if step is None:
            spec.hits += 1
            idx = spec.hits
        else:
            idx = int(step)
        if spec.at <= idx < spec.at + spec.times:
            if not replay:
                spec.fired += 1
                _count(site)
            return True
    return False


def fire_in(site, start, count):
    """Step-window variant for fused launches: fires when ANY step in
    [start, start+count) falls inside the armed window."""
    _ensure()
    spec = _ACTIVE.get(site)
    if spec is None:
        return False
    with _LOCK:
        replay = _replaying(site)
        if spec.fired >= spec.times and not replay:
            return False
        lo, hi = spec.at, spec.at + spec.times
        if int(start) < hi and int(start) + int(count) > lo:
            if not replay:
                spec.fired += 1
                _count(site)
            return True
    return False


class forensic_replay(object):
    """Context manager: while active, the ``nan_step`` site replays its
    armed window deterministically — firing decisions ignore the spent
    budget and do not consume it, so a forensic re-run of step N poisons
    exactly the feeds the original run poisoned, while post-forensics
    production steps keep the one-shot budget semantics."""

    def __enter__(self):
        with _LOCK:
            _REPLAY[0] += 1
        return self

    def __exit__(self, *exc):
        with _LOCK:
            _REPLAY[0] = max(0, _REPLAY[0] - 1)
        return False


def maybe_fail(site, step=None, exc=None):
    """Raise at an armed site (InjectedFault — an OSError — by default)."""
    if fire(site, step):
        raise (exc or InjectedFault)(
            'PT_FAULT: injected fault at site %r' % site)


def maybe_sleep(site):
    """Stall-type sites: sleep the armed duration instead of raising.
    Returns True when the fault fired (serving's dispatch loop uses this
    to attribute the stall — e.g. count a ``compile_storm`` batch as a
    cold one for the circuit breaker)."""
    _ensure()
    spec = _ACTIVE.get(site)
    if spec is not None and fire(site):
        time.sleep(spec.sleep_s)
        return True
    return False


def maybe_kill(site='sigterm', step=None, count=1, sig=signal.SIGTERM):
    """Preemption rehearsal: deliver a signal to this process when the
    step window [step, step+count) overlaps the armed window.  Sleeps
    briefly after the kill so CPython delivers the (asynchronous) Python
    signal handler HERE — at the instrumented site — instead of a few
    bytecodes later, keeping the test deterministic."""
    if not active(site):
        return
    hit = (fire_in(site, step, count) if step is not None else fire(site))
    if hit:
        os.kill(os.getpid(), sig)
        for _ in range(100):   # a terminating handler exits long before
            time.sleep(0.01)


def poison_nan(feed_vals, step, count=1):
    """``nan_step`` site: when the launch's step window [step, step+count)
    covers the armed step, the float feeds of exactly the armed steps are
    overwritten with NaN — the loss and every gradient blow up, and the
    executor's fused check_nan verdict trips exactly as it would for a
    real numeric divergence.  With ``row=R`` only batch row R of each
    armed step is poisoned (the batch axis is axis 0 of a per-step feed,
    axis 1 of a ``count>1`` stacked launch), giving forensic row
    bisection an exact ground truth.  Shapes/dtypes are preserved so the
    poisoned launch reuses the same executable (no retrace)."""
    if not active('nan_step') or not fire_in('nan_step', step, count):
        return feed_vals
    import numpy as np
    sp = spec('nan_step')
    row = sp.row
    # armed step ids intersected with this launch's [step, step+count)
    lo = max(int(step), sp.at)
    hi = min(int(step) + int(count), sp.at + sp.times)
    out = {}
    for k, v in feed_vals.items():
        a = np.asarray(v)
        if not np.issubdtype(a.dtype, np.floating):
            out[k] = v
            continue
        b = np.array(a, copy=True)
        if int(count) > 1:
            # stacked launch: leading axis is the step axis
            for s in range(lo, hi):
                i = s - int(step)
                if row is not None and b.ndim >= 2 and 0 <= row < b.shape[1]:
                    b[i, row] = np.nan
                else:
                    b[i] = np.nan
        else:
            if row is not None and b.ndim >= 1 and 0 <= row < b.shape[0]:
                b[row] = np.nan
            else:
                b[...] = np.nan
        out[k] = b
    return out


def stats():
    """{site: (hits, fired)} snapshot for tests/diagnostics."""
    _ensure()
    return {s: (f.hits, f.fired) for s, f in _ACTIVE.items()}
