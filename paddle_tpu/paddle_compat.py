"""`import paddle_tpu.paddle_compat as paddle` — the reference's top-level
`paddle` namespace (batch, reader, dataset) so benchmark/book model code
runs with two import-line changes only.
"""
import sys as _sys

from .batch import batch  # noqa
from . import reader  # noqa
from . import dataset  # noqa

fluid = _sys.modules['paddle_tpu']

__all__ = ['batch', 'reader', 'dataset', 'fluid']
