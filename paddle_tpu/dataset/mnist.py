"""MNIST (parity: python/paddle/dataset/mnist.py).

Synthetic separable digits: each class k has a fixed template image; samples
are template + noise, so classifiers genuinely learn (loss decreases, acc
rises) — suitable for convergence tests and benchmarks.
"""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test']

_TEMPLATES = {}


def _template(label):
    if label not in _TEMPLATES:
        rng = np.random.RandomState(1234 + label)
        t = rng.uniform(-1, 1, (784,)).astype('float32')
        _TEMPLATES[label] = t
    return _TEMPLATES[label]


def _reader(split, n):
    def reader():
        rng = deterministic_rng('mnist', split)
        for i in range(n):
            label = int(rng.randint(0, 10))
            img = _template(label) + \
                rng.normal(0, 0.35, (784,)).astype('float32')
            yield np.clip(img, -1, 1).astype('float32'), label
    return reader


def train():
    return _reader('train', 8192)


def test():
    return _reader('test', 1024)


def fetch():
    pass
