"""PTB language model n-grams (parity: python/paddle/dataset/imikolov.py).

Synthetic Markov-chain text with a fixed transition structure so that a
real LM can learn it.
"""
from .common import deterministic_rng

__all__ = ['train', 'test', 'build_dict']

N_WORDS = 2073  # ref vocab ~2074 with <unk>


def build_dict(min_word_freq=50):
    return {('w%d' % i): i for i in range(N_WORDS)}


def _reader(split, n, word_idx, ngram):
    v = len(word_idx)

    def reader():
        rng = deterministic_rng('imikolov', split)
        # deterministic sparse transition: next = (3*cur + noise) % v
        for i in range(n):
            start = int(rng.randint(0, v))
            seq = [start]
            for _ in range(ngram - 1):
                nxt = (3 * seq[-1] + int(rng.randint(0, 3))) % v
                seq.append(nxt)
            yield tuple(seq)
    return reader


def train(word_idx, n=5, data_type=1):
    return _reader('train', 8192, word_idx, n)


def test(word_idx, n=5, data_type=1):
    return _reader('test', 1024, word_idx, n)
