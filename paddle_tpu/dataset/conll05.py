"""CoNLL-2005 SRL (parity: python/paddle/dataset/conll05.py). Synthetic."""
import numpy as np
from .common import deterministic_rng

__all__ = ['get_dict', 'get_embedding', 'test']

_WORD_V, _VERB_V, _LABEL_V = 44068, 3162, 59


def get_dict():
    word_dict = {('w%d' % i): i for i in range(_WORD_V)}
    verb_dict = {('v%d' % i): i for i in range(_VERB_V)}
    label_dict = {('l%d' % i): i for i in range(_LABEL_V)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = np.random.RandomState(3)
    return rng.normal(0, 0.1, (_WORD_V, 32)).astype('float32')


def _reader(split, n):
    def reader():
        rng = deterministic_rng('conll05', split)
        for i in range(n):
            length = int(rng.randint(5, 40))
            word = rng.randint(0, _WORD_V, (length,)).astype('int64')
            preds = [rng.randint(0, _WORD_V)] * length
            marks = (rng.uniform(size=length) < 0.2).astype('int64')
            label = ((word + marks) % _LABEL_V).astype('int64')
            ctx = [word.tolist()] * 5
            yield (word.tolist(), *ctx, 
                   np.asarray(preds, dtype='int64').tolist(),
                   marks.tolist(), label.tolist())
    return reader


def test():
    return _reader('test', 512)


def train():
    return _reader('train', 4096)
