"""IMDB sentiment (parity: python/paddle/dataset/imdb.py).

Synthetic: two vocab halves carry positive/negative signal; sequences are
variable-length word-id lists + 0/1 label.
"""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test', 'word_dict']

_VOCAB = 5147  # close to the reference's cutoff vocab


def word_dict():
    return {('w%d' % i): i for i in range(_VOCAB)}


def _reader(split, n, word_idx=None):
    v = len(word_idx) if word_idx else _VOCAB

    def reader():
        rng = deterministic_rng('imdb', split)
        for i in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 100))
            half = v // 2
            if label:
                ids = rng.randint(0, half, (length,))
            else:
                ids = rng.randint(half, v - 1, (length,))
            # mix in noise words
            noise = rng.randint(0, v - 1, (length,))
            mask = rng.uniform(size=length) < 0.25
            ids = np.where(mask, noise, ids)
            yield ids.astype('int64').tolist(), label
    return reader


def train(word_idx=None):
    return _reader('train', 4096, word_idx)


def test(word_idx=None):
    return _reader('test', 512, word_idx)
