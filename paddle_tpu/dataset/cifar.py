"""CIFAR-10/100 (parity: python/paddle/dataset/cifar.py). Synthetic."""
import numpy as np
from .common import deterministic_rng

__all__ = ['train10', 'test10', 'train100', 'test100']

_T = {}


def _template(num_classes, label):
    key = (num_classes, label)
    if key not in _T:
        rng = np.random.RandomState(4321 + label + num_classes)
        _T[key] = rng.uniform(0, 1, (3 * 32 * 32,)).astype('float32')
    return _T[key]


def _reader(split, num_classes, n):
    def reader():
        rng = deterministic_rng('cifar%d' % num_classes, split)
        for i in range(n):
            label = int(rng.randint(0, num_classes))
            img = _template(num_classes, label) + \
                rng.normal(0, 0.3, (3 * 32 * 32,)).astype('float32')
            yield np.clip(img, 0, 1).astype('float32'), label
    return reader


def train10():
    return _reader('train', 10, 8192)


def test10():
    return _reader('test', 10, 1024)


def train100():
    return _reader('train', 100, 8192)


def test100():
    return _reader('test', 100, 1024)
