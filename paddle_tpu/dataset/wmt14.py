"""WMT14 en-fr (parity: python/paddle/dataset/wmt14.py).

Synthetic translation pairs: target = deterministic per-token mapping of
source (a learnable copy-ish task).  Yields (src_ids, trg_ids, trg_next).
"""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test']

_START, _END, _UNK = 0, 1, 2


def _reader(split, n, dict_size):
    def reader():
        rng = deterministic_rng('wmt14', split)
        for i in range(n):
            length = int(rng.randint(4, 30))
            src = rng.randint(3, dict_size, (length,)).astype('int64')
            trg = ((src * 7 + 3) % (dict_size - 3) + 3).astype('int64')
            trg_in = np.concatenate([[_START], trg])
            trg_next = np.concatenate([trg, [_END]])
            yield src.tolist(), trg_in.tolist(), trg_next.tolist()
    return reader


def train(dict_size):
    return _reader('train', 4096, dict_size)


def test(dict_size):
    return _reader('test', 512, dict_size)
