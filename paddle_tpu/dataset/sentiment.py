"""Movie-review sentiment (parity: python/paddle/dataset/sentiment.py)."""
from . import imdb

__all__ = ['get_word_dict', 'train', 'test']


def get_word_dict():
    return sorted(imdb.word_dict().items(), key=lambda kv: kv[1])


def train():
    return imdb.train()


def test():
    return imdb.test()
