"""Image transforms (parity: python/paddle/dataset/image.py), numpy-only."""
import numpy as np

__all__ = ['resize_short', 'to_chw', 'center_crop', 'random_crop',
           'left_right_flip', 'simple_transform']


def _chw_to_hwc(im):
    return im.transpose(1, 2, 0) if im.ndim == 3 and im.shape[0] in (1, 3) \
        else im


def resize_short(im, size):
    h, w = im.shape[:2]
    scale = size / min(h, w)
    nh, nw = int(h * scale), int(w * scale)
    ys = (np.arange(nh) * h / nh).astype(int)
    xs = (np.arange(nw) * w / nw).astype(int)
    return im[ys][:, xs]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs, ws = (h - size) // 2, (w - size) // 2
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs = np.random.randint(0, h - size + 1)
    ws = np.random.randint(0, w - size + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    im = resize_short(im, resize_size)
    im = random_crop(im, crop_size) if is_train else \
        center_crop(im, crop_size)
    if is_train and np.random.randint(2):
        im = left_right_flip(im)
    im = to_chw(im).astype('float32')
    if mean is not None:
        im -= np.asarray(mean).reshape(-1, 1, 1)
    return im
