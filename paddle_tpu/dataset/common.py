"""Shared dataset plumbing (parity: python/paddle/dataset/common.py)."""
import os
import numpy as np

__all__ = ['DATA_HOME', 'md5file', 'download', 'cluster_files_reader',
           'deterministic_rng']

DATA_HOME = os.environ.get('PADDLE_TPU_DATA_HOME',
                           os.path.expanduser('~/.cache/paddle_tpu/dataset'))


def md5file(fname):
    import hashlib
    h = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        'zero-egress environment: place files under %s/%s manually'
        % (DATA_HOME, module_name))


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=np.load):
    def reader():
        import glob
        file_list = sorted(glob.glob(files_pattern))
        my_files = file_list[trainer_id::trainer_count]
        for fn in my_files:
            for item in loader(fn):
                yield item
    return reader


def deterministic_rng(name, split):
    """Stable per-(dataset, split) RNG so synthetic data is reproducible."""
    seed = abs(hash((name, split))) % (2 ** 31)
    return np.random.RandomState(seed)
