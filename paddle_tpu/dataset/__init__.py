"""Datasets (parity: python/paddle/dataset/).

Zero-egress environment: the reference downloads from public mirrors; here
each dataset is a DETERMINISTIC synthetic generator with the same reader
API, shapes, dtypes and label/vocab semantics, so every model/unit test runs
unchanged.  Real data can be dropped into $PADDLE_TPU_DATA_HOME with the
reference file layouts and will be picked up where implemented.
"""
from . import mnist  # noqa
from . import cifar  # noqa
from . import uci_housing  # noqa
from . import imdb  # noqa
from . import imikolov  # noqa
from . import wmt14  # noqa
from . import wmt16  # noqa
from . import movielens  # noqa
from . import conll05  # noqa
from . import flowers  # noqa
from . import sentiment  # noqa
from . import mq2007  # noqa
from . import voc2012  # noqa
from . import common  # noqa
from . import image  # noqa

__all__ = ['mnist', 'cifar', 'uci_housing', 'imdb', 'imikolov', 'wmt14',
           'wmt16', 'movielens', 'conll05', 'flowers', 'sentiment',
           'mq2007', 'voc2012', 'common', 'image']
