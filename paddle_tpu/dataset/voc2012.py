"""VOC2012 segmentation (parity: python/paddle/dataset/voc2012.py).
Synthetic image + dense label pairs."""
from .common import deterministic_rng

__all__ = ['train', 'test', 'val']


def _reader(split, n):
    def reader():
        rng = deterministic_rng('voc2012', split)
        for i in range(n):
            img = rng.uniform(0, 1, (3, 64, 64)).astype('float32')
            lbl = (img.sum(0) > 1.5).astype('int32')
            yield img, lbl
    return reader


def train():
    return _reader('train', 512)


def test():
    return _reader('test', 64)


def val():
    return _reader('val', 64)
