"""MovieLens-1M (parity: python/paddle/dataset/movielens.py).

Synthetic user/movie features + rating = f(user, movie) with latent
factors, mirroring the reference record layout:
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
 score).
"""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
           'age_table', 'movie_categories', 'get_movie_title_dict']

_N_USERS, _N_MOVIES, _N_JOBS, _N_CATS, _TITLE_VOCAB = 6040, 3952, 21, 18, 5175
age_table = [1, 18, 25, 35, 45, 50, 56]

_ruser = np.random.RandomState(11)
_rmovie = np.random.RandomState(12)
_UF = _ruser.normal(0, 1, (_N_USERS + 1, 8)).astype('float32')
_MF = _rmovie.normal(0, 1, (_N_MOVIES + 1, 8)).astype('float32')


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {('cat%d' % i): i for i in range(_N_CATS)}


def get_movie_title_dict():
    return {('t%d' % i): i for i in range(_TITLE_VOCAB)}


def _reader(split, n):
    def reader():
        rng = deterministic_rng('movielens', split)
        for i in range(n):
            uid = int(rng.randint(1, _N_USERS + 1))
            mid = int(rng.randint(1, _N_MOVIES + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _N_JOBS))
            n_cat = int(rng.randint(1, 4))
            cats = rng.randint(0, _N_CATS, (n_cat,)).astype('int64').tolist()
            n_tit = int(rng.randint(1, 6))
            title = rng.randint(0, _TITLE_VOCAB,
                                (n_tit,)).astype('int64').tolist()
            score = float(np.clip(
                2.5 + _UF[uid].dot(_MF[mid]) / 3.0 + rng.normal(0, 0.3),
                1.0, 5.0))
            yield [uid], [gender], [age], [job], [mid], cats, title, score
    return reader


def train():
    return _reader('train', 8192)


def test():
    return _reader('test', 1024)
