"""UCI housing (parity: python/paddle/dataset/uci_housing.py).

Synthetic linear-regression data y = x.w + b + noise, 13 features,
matching the reference feature count.
"""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test', 'feature_range']

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

_W = np.random.RandomState(7).uniform(-1, 1, (13,)).astype('float32')
_B = 0.5


def _reader(split, n):
    def reader():
        rng = deterministic_rng('uci_housing', split)
        for i in range(n):
            x = rng.uniform(-1, 1, (13,)).astype('float32')
            y = float(x.dot(_W) + _B + rng.normal(0, 0.05))
            yield x, np.array([y], dtype='float32')
    return reader


def train():
    return _reader('train', 404)


def test():
    return _reader('test', 102)


def feature_range(maximums, minimums):
    pass


def fetch():
    pass
