"""MQ2007 learning-to-rank (parity: python/paddle/dataset/mq2007.py).
Synthetic query groups with 46 features per doc."""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test']

_W = np.random.RandomState(9).uniform(-1, 1, (46,)).astype('float32')


def _reader(split, n, format='pairwise'):
    def reader():
        rng = deterministic_rng('mq2007', split)
        for q in range(n):
            ndocs = int(rng.randint(5, 20))
            feats = rng.uniform(0, 1, (ndocs, 46)).astype('float32')
            rel = (feats.dot(_W) + rng.normal(0, 0.1, ndocs))
            labels = np.digitize(rel, np.quantile(rel, [0.5, 0.8]))
            if format == 'listwise':
                yield labels.astype('float32'), feats
            else:
                order = np.argsort(-rel)
                for a in range(min(3, ndocs - 1)):
                    i, j = order[a], order[-(a + 1)]
                    if labels[i] > labels[j]:
                        yield 1.0, feats[i], feats[j]
    return reader


def train(format='pairwise'):
    return _reader('train', 512, format)


def test(format='pairwise'):
    return _reader('test', 64, format)
