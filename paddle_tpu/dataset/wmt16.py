"""WMT16 en-de (parity: python/paddle/dataset/wmt16.py). Synthetic."""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test', 'get_dict']


def get_dict(lang, dict_size, reverse=False):
    d = {('%s_w%d' % (lang, i)): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _reader(split, n, src_dict_size, trg_dict_size):
    def reader():
        rng = deterministic_rng('wmt16', split)
        for i in range(n):
            length = int(rng.randint(4, 40))
            src = rng.randint(3, src_dict_size, (length,)).astype('int64')
            trg = ((src * 5 + 11) % (trg_dict_size - 3) + 3).astype('int64')
            trg_in = np.concatenate([[0], trg])
            trg_next = np.concatenate([trg, [1]])
            yield src.tolist(), trg_in.tolist(), trg_next.tolist()
    return reader


def train(src_dict_size, trg_dict_size, src_lang='en'):
    return _reader('train', 4096, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang='en'):
    return _reader('test', 512, src_dict_size, trg_dict_size)
