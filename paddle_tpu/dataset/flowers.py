"""Oxford flowers-102 (parity: python/paddle/dataset/flowers.py).
Synthetic 3x224x224 images."""
import numpy as np
from .common import deterministic_rng

__all__ = ['train', 'test', 'valid']

_T = {}


def _template(label):
    if label not in _T:
        rng = np.random.RandomState(777 + label)
        _T[label] = rng.uniform(0, 1, (3, 224, 224)).astype('float32')
    return _T[label]


def _reader(split, n, use_xmap=True):
    def reader():
        rng = deterministic_rng('flowers', split)
        for i in range(n):
            label = int(rng.randint(0, 102))
            img = _template(label) + \
                rng.normal(0, 0.25, (3, 224, 224)).astype('float32')
            yield np.clip(img, 0, 1).astype('float32').flatten(), label
    return reader


def train(use_xmap=True):
    return _reader('train', 2048, use_xmap)


def test(use_xmap=True):
    return _reader('test', 256, use_xmap)


def valid(use_xmap=True):
    return _reader('valid', 256, use_xmap)
