"""Graph drawing helpers (parity: reference fluid/net_drawer.py /
graphviz.py); delegates to debugger's dot export.  `draw_graph` can run
the static linter first (lint=True) so dead ops, shape errors, and
donation conflicts are highlighted in the rendered graph."""
from .debugger import draw_block_graphviz, draw_program_graphviz  # noqa

__all__ = ['draw_graph', 'draw_block_graphviz', 'draw_program_graphviz']


def draw_graph(startup_program, main_program, path='./graph.dot',
               lint=False, feed_names=(), fetch_list=(), **kwargs):
    """Dot dump of main_program's root block.  With lint=True the
    program is linted (Program.lint) and flagged ops/vars are
    color-coded by severity; feed_names/fetch_list anchor the def-use
    and dead-op passes."""
    lint_result = None
    if lint:
        lint_result = main_program.lint(feed_names=feed_names,
                                        fetch_list=fetch_list)
    return draw_program_graphviz(main_program, path=path,
                                 lint_result=lint_result)
