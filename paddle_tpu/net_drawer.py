"""Graph drawing helpers (parity: reference fluid/net_drawer.py /
graphviz.py); delegates to debugger's dot export."""
from .debugger import draw_block_graphviz, draw_program_graphviz  # noqa

__all__ = ['draw_graph', 'draw_block_graphviz', 'draw_program_graphviz']


def draw_graph(startup_program, main_program, path='./graph.dot', **kwargs):
    return draw_program_graphviz(main_program, path=path)
