"""Accumulating evaluators (parity: reference python/paddle/fluid/
evaluator.py — Evaluator, ChunkEvaluator, EditDistance, DetectionMAP).

State vars are persistable program variables updated by accumulation ops
appended to the main program, so accumulation happens ON DEVICE inside the
same jitted train/eval step (the reference appends per-op state updates the
same way); `reset` runs a small fill program and `eval` reads the states.
"""
import numpy as np

from . import layers
from .core import unique_name
from .core.framework import Program, program_guard, default_main_program
from .core.executor import global_scope

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP']


class Evaluator(object):
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper_name = unique_name.generate(name)
        self.main_program = default_main_program()

    def _create_state(self, suffix, dtype, shape):
        block = self.main_program.global_block()
        state = block.create_var(
            name=unique_name.generate('_'.join(
                [self.helper_name, suffix])),
            shape=list(shape), dtype=dtype, persistable=True,
            stop_gradient=True)
        self.states.append(state)
        return state

    def _accumulate(self, state, batch_var):
        """state += batch_var, in place on the persistable state."""
        block = self.main_program.global_block()
        if batch_var.dtype != state.dtype:
            cast = block.create_var(dtype=state.dtype)
            block.append_op(type='cast', inputs={'X': batch_var},
                            outputs={'Out': cast},
                            attrs={'out_dtype': state.dtype})
            batch_var = cast
        block.append_op(type='elementwise_add',
                        inputs={'X': state, 'Y': batch_var},
                        outputs={'Out': state}, attrs={'axis': -1})

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
            with program_guard(reset_program):
                blk = reset_program.global_block()
                for s in self.states:
                    mirror = blk.create_var(name=s.name, shape=s.shape,
                                            dtype=s.dtype, persistable=True)
                    blk.append_op(type='fill_constant', inputs={},
                                  outputs={'Out': mirror},
                                  attrs={'shape': list(s.shape),
                                         'value': 0.0, 'dtype': s.dtype})
        executor.run(reset_program)

    def _state_value(self, state):
        return np.asarray(global_scope().vars[state.name])

    def eval(self, executor, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Accumulated chunk P/R/F1 (ref evaluator.py ChunkEvaluator; chunk
    semantics from operators/chunk_eval_op.h via layers.chunk_eval)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super(ChunkEvaluator, self).__init__('chunk_eval')
        self.num_infer_chunks = self._create_state(
            'num_infer_chunks', 'int64', [1])
        self.num_label_chunks = self._create_state(
            'num_label_chunks', 'int64', [1])
        self.num_correct_chunks = self._create_state(
            'num_correct_chunks', 'int64', [1])
        (precision, recall, f1, ni, nl, nc) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self._accumulate(self.num_infer_chunks, ni)
        self._accumulate(self.num_label_chunks, nl)
        self._accumulate(self.num_correct_chunks, nc)
        self.metrics = [precision, recall, f1]

    def eval(self, executor, eval_program=None):
        ni = float(self._state_value(self.num_infer_chunks).sum())
        nl = float(self._state_value(self.num_label_chunks).sum())
        nc = float(self._state_value(self.num_correct_chunks).sum())
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return np.array(precision), np.array(recall), np.array(f1)


class EditDistance(Evaluator):
    """Accumulated average edit distance + instance error rate
    (ref evaluator.py EditDistance)."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super(EditDistance, self).__init__('edit_distance')
        self.total_distance = self._create_state(
            'total_distance', 'float32', [1])
        self.seq_num = self._create_state('seq_num', 'int64', [1])
        self.instance_error = self._create_state(
            'instance_error', 'int64', [1])
        distances, seq_num = layers.edit_distance(
            input=input, label=label, normalized=False,
            ignored_tokens=ignored_tokens)
        sum_d = layers.reduce_sum(distances)
        zero = layers.fill_constant([1], 'float32', 0.0)
        err = layers.reduce_sum(layers.cast(distances > zero, 'int64'))
        self._accumulate(self.total_distance, sum_d)
        self._accumulate(self.seq_num, seq_num)
        self._accumulate(self.instance_error, err)
        self.metrics = [sum_d, seq_num]

    def eval(self, executor, eval_program=None):
        total = float(self._state_value(self.total_distance).sum())
        n = float(self._state_value(self.seq_num).sum())
        err = float(self._state_value(self.instance_error).sum())
        avg = total / n if n else 0.0
        rate = err / n if n else 0.0
        return np.array(avg, 'float32'), np.array(rate, 'float32')


class DetectionMAP(Evaluator):
    """Accumulated detection mAP (ref evaluator.py DetectionMAP).

    The reference op threads pos_count/true_pos/false_pos state through
    every batch and recomputes AP over the union; here each batch's mAP
    comes from the stateless layers.detection_map and the accumulated
    value is a **detection-count-weighted** running mean (weight =
    `detect_count` when supplied, else 1 per batch) — equal to the global
    mAP when per-batch score distributions are comparable, and documented
    as the TPU-native simplification (no ragged cross-batch state
    tensors)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral',
                 detect_count=None, label_count=None):
        super(DetectionMAP, self).__init__('map_eval')
        if gt_box is not None and gt_label is not None and \
                gt_label is not gt_box:
            label = layers.concat([
                layers.cast(gt_label, 'float32'), gt_box] + (
                    [layers.cast(gt_difficult, 'float32')]
                    if gt_difficult is not None else []), axis=-1)
        else:
            label = gt_label
        cur_map = layers.detection_map(
            input, label, class_num, background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version,
            detect_count=detect_count, label_count=label_count)
        self.cur_map = cur_map
        self.sum_map = self._create_state('sum_map', 'float32', [1])
        self.weight_sum = self._create_state('weight_sum', 'float32', [1])
        if detect_count is not None:
            wt = layers.reduce_sum(layers.cast(detect_count, 'float32'))
            wt = layers.reshape(wt, [1])
        else:
            wt = layers.fill_constant([1], 'float32', 1.0)
        self._accumulate(self.sum_map, cur_map * wt)
        self._accumulate(self.weight_sum, wt)
        # in-graph accumulated mean, fetchable every batch (parity with the
        # reference's accum_map output of detection_map's accumulating mode)
        self.accum_map = self.sum_map / layers.elementwise_max(
            self.weight_sum, layers.fill_constant([1], 'float32', 1e-6))
        self.metrics = [cur_map, self.accum_map]

    def get_map_var(self):
        return self.cur_map, self.accum_map

    def eval(self, executor, eval_program=None):
        s = float(self._state_value(self.sum_map).sum())
        n = float(self._state_value(self.weight_sum).sum())
        return np.array(s / n if n else 0.0, 'float32')
