"""Program debugging / visualization.

Parity: reference python/paddle/fluid/debugger.py (pprint_program_codes,
draw_block_graphviz) and graphviz.py.  Emits human-readable program listings
and Graphviz .dot files without needing the graphviz binary.
"""
import os
import re

from .core.framework import Parameter, Program

__all__ = ['pprint_program_codes', 'pprint_block_codes',
           'draw_block_graphviz', 'program_to_code']

_RESERVED = re.compile(r'[^A-Za-z0-9_]')


def _code_of_var(v):
    flags = []
    if isinstance(v, Parameter):
        flags.append('param')
    elif v.persistable:
        flags.append('persist')
    if v.stop_gradient:
        flags.append('stop_grad')
    if v.lod_level:
        flags.append('lod=%d' % v.lod_level)
    return '%s : %s%s %s' % (v.name, v.dtype, list(v.shape or ()),
                             ','.join(flags))


def _code_of_op(op):
    ins = ', '.join('%s=[%s]' % (slot, ', '.join(names))
                    for slot, names in sorted(op.inputs.items()))
    outs = ', '.join('%s=[%s]' % (slot, ', '.join(names))
                     for slot, names in sorted(op.outputs.items()))
    attrs = {k: v for k, v in op.attrs.items() if k != 'op_role'}
    astr = ''
    if attrs:
        astr = ' {%s}' % ', '.join(
            '%s=%r' % (k, _short(v)) for k, v in sorted(attrs.items()))
    return '{%s} = %s(%s)%s' % (outs, op.type, ins, astr)


def _short(v):
    s = repr(v)
    return v if len(s) <= 60 else s[:57] + '...'


def pprint_block_codes(block, show_backward=True):
    lines = ['block[%d] parent=%d {' % (block.idx, block.parent_idx)]
    for name in sorted(block.vars):
        lines.append('  var  ' + _code_of_var(block.vars[name]))
    for op in block.ops:
        lines.append('  op   ' + _code_of_op(op))
    lines.append('}')
    return '\n'.join(lines)


def program_to_code(program):
    return '\n'.join(pprint_block_codes(b) for b in program.blocks)


def pprint_program_codes(program, stream=None):
    code = program_to_code(program)
    if stream is None:
        print(code)
    else:
        stream.write(code + '\n')
    return code


def draw_block_graphviz(block, highlights=None, path='./graph.dot'):
    """Write a Graphviz dot file of the block's op/var dataflow graph."""
    highlights = set(highlights or ())

    def vid(name):
        return 'var_' + _RESERVED.sub('_', name)

    lines = ['digraph G {', '  rankdir=TB;']
    seen_vars = set()

    def emit_var(name):
        if name in seen_vars:
            return
        seen_vars.add(name)
        v = block._find_var_recursive(name)
        shape = list(v.shape or ()) if v is not None else '?'
        color = ('red' if name in highlights else
                 'lightblue' if isinstance(v, Parameter) else 'white')
        lines.append(
            '  %s [label="%s\\n%s" shape=oval style=filled '
            'fillcolor=%s];' % (vid(name), name, shape, color))

    for i, op in enumerate(block.ops):
        oid = 'op_%d' % i
        lines.append('  %s [label="%s" shape=box style=filled '
                     'fillcolor=lightgrey];' % (oid, op.type))
        for n in op.input_names():
            emit_var(n)
            lines.append('  %s -> %s;' % (vid(n), oid))
        for n in op.output_names():
            emit_var(n)
            lines.append('  %s -> %s;' % (oid, vid(n)))
    lines.append('}')
    dot = '\n'.join(lines)
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, 'w') as f:
            f.write(dot)
    return dot


def draw_program_graphviz(program, path='./graph.dot'):
    return draw_block_graphviz(program.global_block(), path=path)
