"""Program debugging / visualization.

Parity: reference python/paddle/fluid/debugger.py (pprint_program_codes,
draw_block_graphviz) and graphviz.py.  Emits human-readable program listings
and Graphviz .dot files without needing the graphviz binary.
"""
import os
import re

from .core.framework import Parameter, Program

__all__ = ['pprint_program_codes', 'pprint_block_codes',
           'draw_block_graphviz', 'program_to_code']

_RESERVED = re.compile(r'[^A-Za-z0-9_]')


def _code_of_var(v):
    flags = []
    if isinstance(v, Parameter):
        flags.append('param')
    elif v.persistable:
        flags.append('persist')
    if v.stop_gradient:
        flags.append('stop_grad')
    if v.lod_level:
        flags.append('lod=%d' % v.lod_level)
    return '%s : %s%s %s' % (v.name, v.dtype, list(v.shape or ()),
                             ','.join(flags))


def _code_of_op(op):
    ins = ', '.join('%s=[%s]' % (slot, ', '.join(names))
                    for slot, names in sorted(op.inputs.items()))
    outs = ', '.join('%s=[%s]' % (slot, ', '.join(names))
                     for slot, names in sorted(op.outputs.items()))
    attrs = {k: v for k, v in op.attrs.items() if k != 'op_role'}
    astr = ''
    if attrs:
        astr = ' {%s}' % ', '.join(
            '%s=%r' % (k, _short(v)) for k, v in sorted(attrs.items()))
    return '{%s} = %s(%s)%s' % (outs, op.type, ins, astr)


def _short(v):
    s = repr(v)
    return v if len(s) <= 60 else s[:57] + '...'


def pprint_block_codes(block, show_backward=True):
    lines = ['block[%d] parent=%d {' % (block.idx, block.parent_idx)]
    for name in sorted(block.vars):
        lines.append('  var  ' + _code_of_var(block.vars[name]))
    for op in block.ops:
        lines.append('  op   ' + _code_of_op(op))
    lines.append('}')
    return '\n'.join(lines)


def program_to_code(program):
    return '\n'.join(pprint_block_codes(b) for b in program.blocks)


def pprint_program_codes(program, stream=None):
    code = program_to_code(program)
    if stream is None:
        print(code)
    else:
        stream.write(code + '\n')
    return code


# op fill colors per worst lint severity (analysis.LintResult)
_LINT_OP_COLORS = {'error': 'tomato', 'warning': 'orange',
                   'info': 'khaki'}
_LINT_VAR_COLORS = {'error': 'lightpink', 'warning': 'moccasin',
                    'info': 'lightyellow'}


def _lint_maps(block, lint_result):
    """(op_index -> severity, var name -> (severity, codes)) for this
    block, from a LintResult (analysis/diagnostics.py)."""
    if lint_result is None:
        return {}, {}
    op_sev = {op_i: sev
              for (b_i, op_i), sev in lint_result.op_findings().items()
              if b_i == block.idx}
    var_sev = {}
    rank = {'info': 0, 'warning': 1, 'error': 2}
    for d in lint_result:
        if d.var is None or (d.block_idx is not None and
                             d.block_idx != block.idx):
            continue
        sev, codes = var_sev.get(d.var, ('info', []))
        if rank[d.severity] >= rank[sev]:
            sev = d.severity
        var_sev[d.var] = (sev, codes + [d.code])
    return op_sev, var_sev


def draw_block_graphviz(block, highlights=None, path='./graph.dot',
                        lint_result=None):
    """Write a Graphviz dot file of the block's op/var dataflow graph.

    With `lint_result` (a LintResult from Program.lint()), flagged ops
    and vars are color-coded by worst severity — dead ops, shape
    mismatches, and donation conflicts become visible in the dump — and
    flagged ops grow a tooltip-style second label line with the codes.
    """
    highlights = set(highlights or ())
    op_sev, var_sev = _lint_maps(block, lint_result)
    op_codes = {}
    if lint_result is not None:
        for d in lint_result:
            if d.op_index is not None and d.block_idx == block.idx:
                op_codes.setdefault(d.op_index, []).append(d.code)

    def vid(name):
        return 'var_' + _RESERVED.sub('_', name)

    lines = ['digraph G {', '  rankdir=TB;']
    seen_vars = set()

    def emit_var(name):
        if name in seen_vars:
            return
        seen_vars.add(name)
        v = block._find_var_recursive(name)
        shape = list(v.shape or ()) if v is not None else '?'
        label = '%s\\n%s' % (name, shape)
        if name in var_sev:
            sev, codes = var_sev[name]
            color = _LINT_VAR_COLORS[sev]
            label += '\\n' + ','.join(sorted(set(codes)))
        elif name in highlights:
            color = 'red'
        elif isinstance(v, Parameter):
            color = 'lightblue'
        else:
            color = 'white'
        lines.append(
            '  %s [label="%s" shape=oval style=filled '
            'fillcolor=%s];' % (vid(name), label, color))

    for i, op in enumerate(block.ops):
        oid = 'op_%d' % i
        label = op.type
        color = 'lightgrey'
        if i in op_sev:
            color = _LINT_OP_COLORS[op_sev[i]]
            label += '\\n' + ','.join(sorted(set(op_codes.get(i, ()))))
        lines.append('  %s [label="%s" shape=box style=filled '
                     'fillcolor=%s];' % (oid, label, color))
        for n in op.input_names():
            emit_var(n)
            lines.append('  %s -> %s;' % (vid(n), oid))
        for n in op.output_names():
            emit_var(n)
            lines.append('  %s -> %s;' % (oid, vid(n)))
    lines.append('}')
    dot = '\n'.join(lines)
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, 'w') as f:
            f.write(dot)
    return dot


def draw_program_graphviz(program, path='./graph.dot', lint_result=None,
                          feed_names=(), fetch_list=()):
    """Dot dump of the root block; pass lint_result (or let it run the
    linter itself via lint_result='auto') to color-code findings."""
    if lint_result == 'auto':
        lint_result = program.lint(feed_names=feed_names,
                                   fetch_list=fetch_list)
    return draw_block_graphviz(program.global_block(), path=path,
                               lint_result=lint_result)
