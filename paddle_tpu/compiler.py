"""CompiledProgram / strategies (parity: python/paddle/fluid/compiler.py).

The reference's BuildStrategy/ExecutionStrategy tune the SSA-graph executor
(reduce strategy, num threads...).  Under whole-block XLA lowering most knobs
are moot; `with_data_parallel` maps to a device-mesh data-parallel execution
(parallel/parallel_executor.py).
"""
from .core.executor import _CompiledProgramBase

__all__ = ['CompiledProgram', 'BuildStrategy', 'ExecutionStrategy']


class BuildStrategy(object):
    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = False
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True   # XLA always fuses
        self.fuse_elewise_add_act_ops = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.allow_op_delay = False


class CompiledProgram(_CompiledProgramBase):
    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._data_parallel = False
        self._places = None
        self._loss_name = None
        self._pe = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        return self

    def with_inference_optimize(self, config):
        return self

    def _run(self, exe, feed, fetch_list, scope, return_numpy):
        if not self._data_parallel:
            return exe.run(self._program, feed=feed, fetch_list=fetch_list,
                           scope=scope, return_numpy=return_numpy)
        if self._pe is None:
            from .parallel.parallel_executor import ParallelExecutor
            self._pe = ParallelExecutor(
                use_cuda=False, loss_name=self._loss_name,
                main_program=self._program,
                build_strategy=self._build_strategy)
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        return self._pe.run(fetch_names, feed=feed,
                            return_numpy=return_numpy)
