"""CompiledProgram / strategies (parity: python/paddle/fluid/compiler.py).

The reference's BuildStrategy/ExecutionStrategy tune the SSA-graph executor
(reduce strategy, num threads...).  Under whole-block XLA lowering most knobs
are moot; `with_data_parallel` maps to a device-mesh data-parallel execution
(parallel/parallel_executor.py).

`ExecutionStrategy.num_iteration_per_drop_scope` keeps its reference role
(amortize per-iteration executor overhead) but maps to the TPU-native
mechanism: K > 1 routes a list-of-dicts feed through Executor.run_steps,
fusing K iterations into ONE device launch (a jitted lax.scan) instead of
merely deferring scope cleanup.
"""
import numpy as np

from . import observability as _obs
from .core.executor import _CompiledProgramBase

__all__ = ['CompiledProgram', 'BuildStrategy', 'ExecutionStrategy']


class BuildStrategy(object):
    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = False
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True   # XLA always fuses
        self.fuse_elewise_add_act_ops = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.allow_op_delay = False


class CompiledProgram(_CompiledProgramBase):
    def __init__(self, program, build_strategy=None, exec_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy
        self._data_parallel = False
        self._places = None
        self._loss_name = None
        self._pe = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        self._places = places
        return self

    def with_inference_optimize(self, config):
        return self

    def prewarm(self, exe, feed, fetch_list, scope=None, steps=None):
        """AOT pre-warm (core/compile_cache.py): compile — or load from
        the persistent cache — every executable this program will need
        for the given feed signature, before the first real batch.

        `feed` maps name -> example array or (shape, dtype) spec.  With
        `steps=None` the fused K from num_iteration_per_drop_scope is
        used; both the K-step scan AND the single-step executable are
        prepared (the single-step one also serves ragged tails, which
        Executor.run_steps routes through it).  Pass an explicit list of
        step counts to control exactly what gets compiled.

        Returns the list of disk fingerprints (None entries when the
        persistent tier is disabled)."""
        k = self._steps_per_launch
        if steps is None:
            plan = [None] if k <= 1 else [None, k]
        elif isinstance(steps, (list, tuple)):
            plan = list(steps)
        else:
            plan = [steps]
        with _obs.span('compiled_program.prewarm', cat='compile',
                       plan=str(plan)):
            if self._data_parallel:
                pe = self._pe_for(exe)
                return [pe.prepare(self._program, feed=feed,
                                   fetch_list=fetch_list, steps=s)
                        for s in plan]
            return [exe.prepare(self._program, feed=feed,
                                fetch_list=fetch_list, scope=scope, steps=s)
                    for s in plan]

    @property
    def _steps_per_launch(self):
        es = self._exec_strategy
        return max(1, int(getattr(es, 'num_iteration_per_drop_scope', 1)
                          if es is not None else 1))

    def _pe_for(self, exe):
        if self._pe is None:
            from .parallel.parallel_executor import ParallelExecutor
            self._pe = ParallelExecutor(
                use_cuda=False, loss_name=self._loss_name,
                main_program=self._program,
                build_strategy=self._build_strategy)
        return self._pe

    def _run(self, exe, feed, fetch_list, scope, return_numpy,
             as_futures=False):
        k = self._steps_per_launch
        if k > 1 and isinstance(feed, (list, tuple)):
            # num_iteration_per_drop_scope > 1 + a list of per-step feeds:
            # run the whole list K iterations per device launch and return
            # the per-step fetches stacked over ALL steps
            return self._run_steps(exe, list(feed), fetch_list, None,
                                   scope, return_numpy,
                                   as_futures=as_futures)
        if not self._data_parallel:
            return exe.run(self._program, feed=feed, fetch_list=fetch_list,
                           scope=scope, return_numpy=return_numpy,
                           as_futures=as_futures)
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        return self._pe_for(exe).run(fetch_names, feed=feed,
                                     return_numpy=return_numpy,
                                     as_futures=as_futures)

    def _run_steps(self, exe, feed_list, fetch_list, steps, scope,
                   return_numpy, as_futures=False):
        """K-iterations-per-launch execution: chunk the per-step feeds by
        num_iteration_per_drop_scope and fuse each chunk into one launch."""
        k = steps or self._steps_per_launch
        if self._data_parallel:
            runner = self._pe_for(exe)
            run_kwargs = {}
        else:
            runner = exe
            run_kwargs = {'scope': scope}
        if isinstance(feed_list, dict):   # pre-stacked superbatch
            return runner.run_steps(self._program, feed_list=feed_list,
                                    fetch_list=fetch_list, steps=k,
                                    return_numpy=return_numpy,
                                    as_futures=as_futures, **run_kwargs)
        chunks = [feed_list[i:i + k] for i in range(0, len(feed_list), k)]
        if _obs.enabled() and len(chunks) > 1 and len(chunks[-1]) != k:
            # a ragged tail chunk lowers a SECOND executable (steps=len
            # differs) — flag it on the timeline, it reads as a mystery
            # compile otherwise
            _obs.instant('compiled_program.ragged_tail', cat='compile',
                         args={'k': k, 'tail': len(chunks[-1])})
        with _obs.span('compiled_program.run_steps', cat='launch',
                       chunks=len(chunks), k=k):
            outs = [runner.run_steps(self._program, feed_list=c,
                                     fetch_list=fetch_list, steps=len(c),
                                     return_numpy=return_numpy,
                                     as_futures=as_futures,
                                     **run_kwargs)
                    for c in chunks]
        if len(outs) == 1:
            return outs[0]
        if as_futures:
            # concatenate the chunk fetches ON DEVICE and re-wrap: the
            # multi-chunk path stays sync-free end to end
            from .core.async_runtime import FetchFuture
            return [FetchFuture(_jnp_concat([o[i].device() for o in outs]))
                    for i in range(len(outs[0]))]
        cat = np.concatenate if return_numpy else _jnp_concat
        return [cat([o[i] for o in outs]) for i in range(len(outs[0]))]


def _jnp_concat(arrs):
    import jax.numpy as jnp
    return jnp.concatenate(arrs)
