"""DataFeedDesc (parity: reference fluid/data_feed_desc.py, data_feed.proto)
— re-exported from the native C++ datafeed pipeline."""
from .native import DataFeedDesc  # noqa: F401

__all__ = ['DataFeedDesc']
