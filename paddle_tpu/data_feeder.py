"""DataFeeder — convert python/numpy minibatch rows into feed dicts.

Parity: reference python/paddle/fluid/data_feeder.py.  Ragged (lod_level>0)
slots become LoDTensors (padded + lengths, core/lod.py).

FeedPrefetcher is the host side of the multi-step execution path
(Executor.run_steps): a bounded background queue that stacks K per-step
feed dicts into one [K, ...] superbatch and device_puts it while the
device runs the current launch, so host->device transfer overlaps compute.

FeedBucketer is the shape-stability half of the compilation-persistence
story (core/compile_cache.py): variable batch/sequence sizes — ragged
epoch tails, LoD sequence lengths — each lower a fresh executable under
whole-block jit.  The bucketer pads the leading batch dim (and declared
sequence dims) up to a small set of boundaries and threads a validity
mask feed, so arbitrary feed streams collapse onto a handful of compile
signatures instead of one trace per shape.
"""
import os
import queue
import threading
import time

import numpy as np

from .core.framework import Variable, default_main_program
from .core.lod import create_lod_tensor
from .core.dtypes import convert_dtype
from .core.retry import retry_with_backoff
from . import observability as _obs
from .observability import flight as _flight
from .testing import faults as _faults

__all__ = ['DataFeeder', 'FeedPrefetcher', 'FeedBucketer',
           'SampleQuarantine']


def _default_boundaries():
    """Powers-of-two with 1.5x midpoints: dense enough that pad waste
    stays under ~25%, sparse enough that a whole training run touches
    only a few signatures.  Override per-instance or via PT_BUCKETS."""
    env = os.environ.get('PT_BUCKETS')
    if env:
        return sorted(int(b) for b in env.replace(',', ' ').split())
    bounds = [1, 2, 4, 6, 8]
    while bounds[-1] < 65536:
        b = bounds[-1]
        # 8, 12, 16, 24, 32, 48, 64, 96, 128, ...
        bounds.append(b + b // 2 if (b & (b - 1)) == 0 else b + b // 3)
    return bounds


class FeedBucketer(object):
    """Pad feeds up to bucket boundaries so variable shapes reuse a small
    fixed set of executables.

    * **Batch dim** (axis 0 of every feed whose leading dim matches the
      batch): padded up to the smallest boundary >= the true batch by
      edge-replicating the last row (every op stays well-defined on pad
      rows; they carry no NaN/div-by-zero hazard).  When `mask_name` is
      set, a float32 ``[B', 1]`` validity mask (1 real / 0 pad) is added
      to the feed — thread it through loss/metric reductions
      (``loss = sum(per_example * mask) / sum(mask)``) and padded rows
      contribute exactly zero to the loss AND to every gradient.
    * **Sequence dims**: feeds named in `seq_names` get axis 1 padded up
      to a boundary with zeros.  LoDTensor feeds already carry true
      lengths in their ``@LENGTH`` companion, and every sequence op masks
      by length — so sequence-bucketed feeds need no extra mask.

    Pad waste is observable: ``bucketer.rows_real`` / ``bucketer.rows_pad``
    counters and the ``bucketer.pad_waste`` gauge (last batch's padded
    fraction) land in the PR 2 metrics registry.

    Compose with the prefetcher as ``FeedPrefetcher(feeds, bucketer=b)``
    or wrap any feed iterable with :meth:`wrap`.
    """

    def __init__(self, boundaries=None, mask_name=None, seq_names=(),
                 pad_mode='edge'):
        self.boundaries = sorted(int(b) for b in
                                 (boundaries or _default_boundaries()))
        if not self.boundaries or self.boundaries[0] < 1:
            raise ValueError('bucket boundaries must be positive ints')
        self.mask_name = mask_name
        self.seq_names = tuple(seq_names)
        if pad_mode not in ('edge', 'zero'):
            raise ValueError("pad_mode must be 'edge' or 'zero'")
        self.pad_mode = pad_mode
        # distinct batch boundaries this instance has materialized — each
        # one is a compile signature, so unbounded growth here (huge
        # batches quantizing to ever-new multiples of the top boundary)
        # is a compile-cache leak; metered as the bucketer.bucket_count
        # gauge and readable via bucket_count()
        self._buckets_seen = set()

    def boundary(self, n):
        """Smallest boundary >= n; beyond the largest boundary, the next
        multiple of it (so huge batches still quantize, coarsely)."""
        n = int(n)
        if n < 1:
            raise ValueError('bucket size must be >= 1, got %d' % n)
        for b in self.boundaries:
            if b >= n:
                return b
        top = self.boundaries[-1]
        return ((n + top - 1) // top) * top

    def _pad_axis(self, arr, axis, target):
        arr = np.asarray(arr)
        gap = target - arr.shape[axis]
        if gap <= 0:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, gap)
        if self.pad_mode == 'edge' and axis == 0 and arr.shape[0] > 0:
            return np.pad(arr, widths, mode='edge')
        return np.pad(arr, widths, mode='constant')

    def bucket_feed(self, feed):
        """One feed dict -> (padded feed dict, true batch size).  The mask
        feed (if configured) is ALWAYS present — a full batch gets all
        ones — so the feed-name set, which is part of the compile
        signature, never wobbles between padded and exact batches."""
        from .core.lod import LoDTensor
        arrays = {k: (v if isinstance(v, LoDTensor) else np.asarray(v))
                  for k, v in feed.items()}
        # consensus batch: the leading dim of the first batched feed;
        # arrays with a different leading dim pass through unpadded
        dims = [d for d in (_leading_dim(v) for v in arrays.values())
                if d is not None]
        if not dims:
            raise ValueError('bucket_feed needs at least one batched feed')
        batch = dims[0]
        target = self.boundary(batch)
        out = {}
        for k, v in arrays.items():
            if isinstance(v, LoDTensor):
                if v.outer_lengths is not None:
                    # nested LoD: the inner-row dim is not the batch —
                    # padding it would break the outer offset table
                    out[k] = v
                    continue
                padded, lengths = v.padded, v.lengths
                if padded.shape[0] == batch:
                    padded = self._pad_axis(padded, 0, target)
                    # edge-replicated lengths keep pad rows non-empty:
                    # a zero-length row would NaN length-normalizing
                    # sequence ops, and NaN * mask 0 is still NaN
                    lengths = self._pad_axis(lengths, 0, target)
                if k in self.seq_names:
                    padded = self._pad_axis(padded, 1,
                                            self.boundary(padded.shape[1]))
                out[k] = LoDTensor(padded, lengths)
                continue
            if v.ndim and v.shape[0] == batch:
                v = self._pad_axis(v, 0, target)
            if k in self.seq_names and v.ndim >= 2:
                v = self._pad_axis(v, 1, self.boundary(v.shape[1]))
            out[k] = v
        if self.mask_name:
            mask = np.zeros((target, 1), np.float32)
            mask[:batch] = 1.0
            out[self.mask_name] = mask
        self._buckets_seen.add(target)
        if _obs.enabled():
            _obs.metrics.gauge('bucketer.bucket_count').set(
                len(self._buckets_seen))
            _obs.metrics.counter('bucketer.batches').inc()
            _obs.metrics.counter('bucketer.rows_real').inc(batch)
            _obs.metrics.counter('bucketer.rows_pad').inc(target - batch)
            _obs.metrics.gauge('bucketer.pad_waste').set(
                (target - batch) / float(target))
        return out, batch

    def bucket_count(self):
        """Distinct batch boundaries materialized so far (== the
        ``bucketer.bucket_count`` gauge)."""
        return len(self._buckets_seen)

    def covered_axes(self, name, lod_level=0):
        """Which axes of feed `name` this bucketer stabilizes onto bucket
        boundaries: axis 0 (batch) always, axis 1 when the feed is named
        in seq_names.  Nested-LoD feeds (lod_level > 1) pass through
        bucket_feed unpadded, so nothing is covered.  The lint retrace-
        hazard pass (analysis/passes/retrace.py) consumes this to decide
        which dynamic dims still threaten a per-shape recompile."""
        if lod_level > 1:
            return set()
        axes = {0}
        if name in self.seq_names:
            axes.add(1)
        return axes

    def wrap(self, feeds):
        """Generator over an iterable of feed dicts, bucketing each.
        Yields just the padded feeds (the mask feed carries validity), so
        the result plugs straight into FeedPrefetcher / run_steps."""
        for f in feeds:
            yield self.bucket_feed(f)[0]

    @staticmethod
    def trim(fetches, batch):
        """Slice per-example fetch arrays back to the true batch size.
        Arrays whose leading dim is not the padded batch (scalar losses,
        stacked [K, B, ...] fetches get their SECOND dim trimmed) pass
        through untouched where no dim matches."""
        out = []
        for f in fetches:
            a = np.asarray(f)
            if a.ndim >= 1 and a.shape[0] >= batch:
                out.append(a[:batch])
            else:
                out.append(a)
        return out


def _leading_dim(v):
    from .core.lod import LoDTensor
    if isinstance(v, LoDTensor):
        return v.padded.shape[0]
    a = np.asarray(v)
    return a.shape[0] if a.ndim else None


class FeedPrefetcher(object):
    """Bounded background prefetch queue over an iterable of feed dicts.

    Pulls per-step feed dicts from `feeds`, stacks every `steps` of them
    on a new leading axis (np.stack on host — ONE device_put per
    superbatch instead of one per step), optionally uploads the stack,
    and parks the result in a bounded queue.  A single worker thread
    preserves order; reader exhaustion flushes the partial tail (its true
    length is yielded alongside) and drains cleanly; a reader exception
    is re-raised in the consumer at the point it would have been read.

    Iterating yields (stacked_feed_dict, k) with k == steps except for
    the final partial superbatch.  Feed Executor.run_steps directly:

        for superbatch, k in FeedPrefetcher(batches, steps=8):
            losses = exe.run_steps(prog, feed_list=superbatch, steps=k,
                                   fetch_list=[loss])
    """

    def __init__(self, feeds, steps=1, capacity=2, to_device=True,
                 bucketer=None, skip_steps=0):
        if steps < 1:
            raise ValueError('steps must be >= 1, got %r' % (steps,))
        if capacity < 1:
            raise ValueError('capacity must be >= 1, got %r' % (capacity,))
        if skip_steps < 0:
            raise ValueError('skip_steps must be >= 0, got %r'
                             % (skip_steps,))
        # bucketing happens on the worker thread, before stacking: padded
        # per-step feeds share one shape, so a ragged epoch tail batch no
        # longer breaks np.stack — nor costs a fresh compile signature
        self._src = iter(bucketer.wrap(feeds) if bucketer is not None
                         else feeds)
        # checkpoint resume: fast-forward past the steps a previous run
        # already consumed (the cursor() of the checkpointed prefetcher)
        self._skip = int(skip_steps)
        self._steps_out = 0
        self._superbatches_out = 0
        self._steps = int(steps)
        self._to_device = to_device
        self._q = queue.Queue(maxsize=int(capacity))
        self._terminal = None   # ('done',) | ('error', exc) | ('closed',)
        # telemetry: is the consumer currently blocked on an empty queue?
        # (pack work done while it ISN'T waiting overlapped its compute)
        self._consumer_waiting = False
        # lifetime totals behind the prefetch.upload_overlap_ratio gauge
        self._upload_s = 0.0
        self._overlap_s = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name='FeedPrefetcher', daemon=True)
        self._thread.start()

    def _pack(self, buf):
        names = set(buf[0])
        for f in buf[1:]:
            if set(f) != names:
                raise ValueError('per-step feeds disagree on keys: %s vs %s'
                                 % (sorted(names), sorted(f)))
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        overlapped = obs_on and not self._consumer_waiting
        stacked = {k: np.stack([np.asarray(f[k]) for f in buf])
                   for k in buf[0]}
        if self._to_device:
            import jax
            stacked = jax.device_put(stacked)
        if obs_on:
            t1 = time.perf_counter()
            dt = t1 - t0
            _obs.metrics.counter('prefetch.superbatches').inc()
            _obs.metrics.counter('prefetch.upload_s').inc(dt)
            self._upload_s += dt
            if overlapped:
                # stacking+upload ran while the consumer was busy running
                # the previous launch — the overlap the prefetcher exists
                # to buy.  Upload time with the consumer parked on the
                # queue is exposed transfer latency instead.
                _obs.metrics.counter('prefetch.upload_overlap_s').inc(dt)
                self._overlap_s += dt
            _obs.metrics.gauge('prefetch.upload_overlap_ratio').set(
                self._overlap_s / self._upload_s if self._upload_s else 0.0)
            _obs.tracing.add_span('prefetch.pack', t0, t1,
                                  cat='prefetch',
                                  args={'steps': len(buf),
                                        'overlapped': overlapped})
            return (stacked, len(buf)), (t0, t1)
        return (stacked, len(buf)), None

    def _put(self, item):
        # bounded put that stays responsive to close(): never blocks
        # forever on a consumer that went away
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                if _obs.enabled():
                    _obs.metrics.gauge('prefetch.queue_depth').set(
                        self._q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def _read_next(self):
        """One reader pull behind the shared transient-IO retry policy
        (core/retry.py): a flaky reader — an NFS blip, an object-store
        hiccup, the deterministic ``feed_read`` fault site — is absorbed
        with bounded backoff instead of killing the trainer.
        StopIteration propagates immediately: exhaustion is not an
        error."""
        def read():
            if _faults.any_active():
                _faults.maybe_fail('feed_read')
            return next(self._src)
        return retry_with_backoff(read, base_delay=0.01, max_delay=0.2,
                                  retry_on=(OSError,),
                                  give_up_on=(StopIteration,),
                                  name='feed_read')

    def _worker(self):
        try:
            skipped = 0
            while skipped < self._skip:
                if self._stop.is_set():
                    return
                try:
                    self._read_next()
                except StopIteration:
                    self._put(('done', None, None))
                    return
                skipped += 1
            if skipped and _obs.enabled():
                _obs.metrics.counter('prefetch.skipped_steps').inc(skipped)
            buf = []
            while True:
                if self._stop.is_set():
                    return
                try:
                    f = self._read_next()
                except StopIteration:
                    break
                buf.append(f)
                if len(buf) == self._steps:
                    if _faults.any_active():
                        _faults.maybe_sleep('prefetch_stall')
                    payload, span = self._pack(buf)
                    if not self._put(('batch', payload, span)):
                        return
                    buf = []
            if buf:
                payload, span = self._pack(buf)
                if not self._put(('batch', payload, span)):
                    return
            self._put(('done', None, None))
        except BaseException as e:  # noqa: BLE001 - relayed to consumer
            self._put(('error', e, None))

    def __iter__(self):
        while True:
            if self._terminal is not None:
                # exhausted/errored/closed: iterating again yields nothing
                # instead of blocking on a queue no worker will ever fill
                return
            obs_on = _obs.enabled()
            starved = obs_on and self._q.empty()
            if obs_on:
                self._consumer_waiting = True
                t0 = time.perf_counter()
            kind, payload, pack_span = self._q.get()
            if obs_on:
                self._consumer_waiting = False
                _obs.metrics.gauge('prefetch.queue_depth').set(
                    self._q.qsize())
                if starved:
                    wait_t1 = time.perf_counter()
                    wait = wait_t1 - t0
                    # split the empty-queue wait: time spent with an
                    # upload IN FLIGHT (the pack span overlapped the wait)
                    # is transfer latency, not reader starvation — the two
                    # need different fixes (bigger capacity / async upload
                    # vs a faster reader)
                    overlap = 0.0
                    if pack_span is not None:
                        overlap = max(0.0, min(wait_t1, pack_span[1]) -
                                      max(t0, pack_span[0]))
                        if overlap <= 1e-4:
                            overlap = 0.0
                    if overlap > 0.0:
                        _obs.metrics.counter('prefetch.upload_waits').inc()
                        _obs.metrics.counter(
                            'prefetch.upload_wait_s').inc(overlap)
                        _obs.tracing.add_span(
                            'prefetch.upload_wait', t0, wait_t1,
                            cat='prefetch')
                    starve_s = wait - overlap
                    if overlap == 0.0 or starve_s > 1e-4:
                        # the training loop wanted the next superbatch and
                        # the queue was empty: the reader is the bottleneck
                        _obs.metrics.counter(
                            'prefetch.starvation_count').inc()
                        _obs.metrics.counter(
                            'prefetch.starvation_s').inc(starve_s)
                        _obs.tracing.add_span(
                            'prefetch.starved', t0, wait_t1,
                            cat='prefetch')
            if kind == 'done':
                self._terminal = ('done',)
                return
            if kind == 'error':
                self._terminal = ('error', payload)
                raise payload
            self._superbatches_out += 1
            self._steps_out += payload[1]
            yield payload

    def cursor(self):
        """Absolute position in the feed stream — save it in checkpoint
        ``extra_meta`` and pass ``skip_steps=cursor()['steps']`` to the
        resumed prefetcher to fast-forward past consumed batches."""
        return {'steps': self._skip + self._steps_out,
                'superbatches': self._superbatches_out,
                'skipped': self._skip}

    def close(self):
        """Stop the worker and release the queue (safe to call twice)."""
        if self._terminal is None:
            self._terminal = ('closed',)
        self._stop.set()
        while True:  # unblock a worker parked on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError('feed_list should hold Variables')
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            shape = each_var.shape
            # strip batch (and time, for lod vars) dims
            if each_var.lod_level > 0:
                shape = shape[2:]
            else:
                shape = shape[1:]
            self.feed_shapes.append(shape)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        feed = {}
        for i, name in enumerate(self.feed_names):
            dtype = convert_dtype(self.feed_dtypes[i])
            shape = self.feed_shapes[i]
            col = [row[i] for row in rows]
            if self.feed_lod_level[i] > 0:
                seqs = [np.asarray(c, dtype=dtype) for c in col]
                seqs = [s.reshape(len(s), *shape) if shape else
                        s.reshape(len(s), 1) for s in
                        (s.reshape(-1) if s.ndim == 1 else s for s in seqs)]
                feed[name] = create_lod_tensor([s for s in seqs])
            else:
                tshape = tuple(int(abs(d)) for d in shape)
                # each element reshapes to the slot shape INDIVIDUALLY
                # (reference DataToLoDTensorConverter semantics): rows
                # may arrive flat (mnist 784) or already shaped
                elems = [np.asarray(c, dtype=dtype).reshape(tshape)
                         for c in col]
                feed[name] = (np.stack(elems) if elems else
                              np.zeros((0,) + tshape, dtype))
        return feed

    def feed_parallel(self, iterable, num_places=None):
        # one merged batch; sharding over devices happens inside pjit
        merged = []
        for batch in iterable:
            merged.extend(batch)
        return self.feed(merged)

    def decorate_reader(self, reader, multi_devices=False, num_places=None,
                        drop_last=True):
        def _reader():
            for batch in reader():
                yield self.feed(batch)
        return _reader


def default_sample_index(step, row, batch_size):
    """Default (step, batch row) -> reader sample index mapping: a
    single-pass sequential reader emitting fixed-size batches.  Epoch
    loops or shuffled readers must supply their own ``index_of`` so
    quarantined indices stay stable across passes."""
    return int(step) * int(batch_size) + int(row)


class SampleQuarantine(object):
    """Persistent set of condemned reader sample indices.

    When forensics (train/forensics.py) names the batch rows that
    poisoned a step, `add` records their reader indices here and
    `apply` keeps them out of every future feed by replacing each
    quarantined row with the nearest healthy row of the same batch —
    shapes stay fixed, so no retrace, and a reference run with the same
    quarantine pre-seeded builds bitwise-identical feeds.  The set rides
    checkpoint META (`state`/`restore`, train/checkpoint.py) so a
    resumed run never re-trips on a sample it already condemned; an
    optional ``path`` additionally persists it as a standalone JSON file
    for inspection and cross-job sharing.
    """

    def __init__(self, path=None, index_of=None):
        self._set = set()
        self.path = path
        self.index_of = index_of or default_sample_index
        if path and os.path.exists(path):
            self._load()

    def __len__(self):
        return len(self._set)

    def __contains__(self, idx):
        return int(idx) in self._set

    def state(self):
        """JSON-able snapshot (sorted sample indices)."""
        return sorted(self._set)

    def restore(self, state):
        """Merge a snapshot back in — union, never shrink: an index
        condemned after the snapshot was taken stays condemned."""
        self._set.update(int(i) for i in (state or ()))
        if _obs.enabled():
            _obs.metrics.gauge('feed.quarantine_size').set(len(self._set))

    def add(self, indices, reason='forensics'):
        """Quarantine reader indices; counts only the NEW ones into
        ``feed.quarantined`` and persists when a path is set."""
        fresh = [int(i) for i in indices if int(i) not in self._set]
        if not fresh:
            return 0
        self._set.update(fresh)
        if _obs.enabled():
            _obs.metrics.counter('feed.quarantined').inc(len(fresh))
            _obs.metrics.gauge('feed.quarantine_size').set(len(self._set))
        _flight.record('feed.quarantine', indices=fresh, reason=reason,
                       total=len(self._set))
        if self.path:
            self._persist()
        return len(fresh)

    def _load(self):
        import json

        def read():
            with open(self.path) as f:
                return json.load(f)
        try:
            data = retry_with_backoff(read, retry_on=(OSError,),
                                      give_up_on=(FileNotFoundError,),
                                      name='quarantine_read')
        except (FileNotFoundError, ValueError):
            return
        self.restore(data.get('indices', ()))

    def _persist(self):
        import json
        payload = json.dumps({'indices': self.state()})

        def write():
            tmp = self.path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(payload)
            os.replace(tmp, self.path)
        retry_with_backoff(write, retry_on=(OSError,),
                           name='quarantine_write')

    # ---------------------------------------------------------- feed-time
    def _clean_rows(self, step, batch):
        """(quarantined rows, replacement row per quarantined row) for one
        step's batch.  Each bad row maps to the NEAREST healthy row
        (preferring earlier), deterministically."""
        bad = [r for r in range(batch)
               if self.index_of(step, r, batch) in self._set]
        if not bad or len(bad) == batch:
            # nothing to do — or nothing healthy left to substitute
            # (the whole batch is condemned; the caller's skip-batch
            # rung handles it)
            if bad and _obs.enabled():
                _obs.metrics.counter('feed.quarantine_saturated').inc()
            return ([], {}) if len(bad) != batch else (bad, {})
        bad_set = set(bad)
        repl = {}
        for r in bad:
            for d in range(1, batch):
                for cand in (r - d, r + d):
                    if 0 <= cand < batch and cand not in bad_set:
                        repl[r] = cand
                        break
                if r in repl:
                    break
        return bad, repl

    def apply(self, feed, step0, steps=1):
        """Return (feed', replaced_count) with quarantined rows replaced.

        Handles the three launch feed forms the executor accepts: one
        per-step dict (batch axis 0), a stacked superbatch dict (step
        axis 0, batch axis 1), or a list of per-step dicts.  Every array
        of the batch's leading size is substituted — labels included —
        so the replacement row is a fully-consistent duplicate sample."""
        if not self._set:
            return feed, 0
        if isinstance(feed, (list, tuple)):
            out = []
            n = 0
            for i, f in enumerate(feed):
                f2, k = self.apply(f, int(step0) + i, 1)
                out.append(f2)
                n += k
            return (list(out) if isinstance(feed, list) else tuple(out)), n
        arrays = {k: np.asarray(v) for k, v in feed.items()}
        if not arrays:
            return feed, 0
        stacked = int(steps) > 1
        dims = [a.shape[1] if stacked else a.shape[0]
                for a in arrays.values()
                if a.ndim >= (2 if stacked else 1)]
        if not dims or len(set(dims)) != 1:
            return feed, 0   # no consistent batch axis to substitute on
        batch = dims[0]
        replaced = 0
        out = dict(feed)
        steps_n = int(steps) if stacked else 1
        for si in range(steps_n):
            step = int(step0) + si
            bad, repl = self._clean_rows(step, batch)
            if not repl:
                continue
            for k, a in arrays.items():
                if a.ndim < (2 if stacked else 1):
                    continue
                b = np.array(np.asarray(out[k]), copy=True)
                for r, src in repl.items():
                    if stacked:
                        b[si, r] = b[si, src]
                    else:
                        b[r] = b[src]
                out[k] = b
            replaced += len(repl)
        if replaced and _obs.enabled():
            _obs.metrics.counter('feed.quarantined_rows').inc(replaced)
        return out, replaced

    def wrap(self, feeds, start_step=0):
        """Wrap a per-step feed iterable: each yielded dict has its
        quarantined rows replaced (step ids count up from start_step).
        Compose under a FeedPrefetcher so quarantine applies before
        superbatch packing."""
        def gen():
            for i, f in enumerate(feeds):
                yield self.apply(f, int(start_step) + i, 1)[0]
        return gen()
