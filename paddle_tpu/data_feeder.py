"""DataFeeder — convert python/numpy minibatch rows into feed dicts.

Parity: reference python/paddle/fluid/data_feeder.py.  Ragged (lod_level>0)
slots become LoDTensors (padded + lengths, core/lod.py).
"""
import numpy as np

from .core.framework import Variable, default_main_program
from .core.lod import create_lod_tensor
from .core.dtypes import convert_dtype

__all__ = ['DataFeeder']


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError('feed_list should hold Variables')
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            shape = each_var.shape
            # strip batch (and time, for lod vars) dims
            if each_var.lod_level > 0:
                shape = shape[2:]
            else:
                shape = shape[1:]
            self.feed_shapes.append(shape)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        feed = {}
        for i, name in enumerate(self.feed_names):
            dtype = convert_dtype(self.feed_dtypes[i])
            shape = self.feed_shapes[i]
            col = [row[i] for row in rows]
            if self.feed_lod_level[i] > 0:
                seqs = [np.asarray(c, dtype=dtype) for c in col]
                seqs = [s.reshape(len(s), *shape) if shape else
                        s.reshape(len(s), 1) for s in
                        (s.reshape(-1) if s.ndim == 1 else s for s in seqs)]
                feed[name] = create_lod_tensor([s for s in seqs])
            else:
                tshape = tuple(int(abs(d)) for d in shape)
                # each element reshapes to the slot shape INDIVIDUALLY
                # (reference DataToLoDTensorConverter semantics): rows
                # may arrive flat (mnist 784) or already shaped
                elems = [np.asarray(c, dtype=dtype).reshape(tshape)
                         for c in col]
                feed[name] = (np.stack(elems) if elems else
                              np.zeros((0,) + tshape, dtype))
        return feed

    def feed_parallel(self, iterable, num_places=None):
        # one merged batch; sharding over devices happens inside pjit
        merged = []
        for batch in iterable:
            merged.extend(batch)
        return self.feed(merged)

    def decorate_reader(self, reader, multi_devices=False, num_places=None,
                        drop_last=True):
        def _reader():
            for batch in reader():
                yield self.feed(batch)
        return _reader
