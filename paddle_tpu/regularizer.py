"""Weight-decay regularizers, appended as grad-transform ops.

Parity: reference python/paddle/fluid/regularizer.py.
"""
from .core.framework import op_role_guard, OpRole

__all__ = ['L1Decay', 'L2Decay', 'L1DecayRegularizer', 'L2DecayRegularizer',
           'append_regularization_ops']


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype)
        block.append_op(type='scale', inputs={'X': param},
                        outputs={'Out': decay},
                        attrs={'scale': self._regularization_coeff,
                               'bias': 0.0, 'bias_after_scale': True})
        block.append_op(type='elementwise_add',
                        inputs={'X': grad, 'Y': decay},
                        outputs={'Out': grad}, attrs={'axis': -1})
        return grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype)
        block.append_op(type='sign', inputs={'X': param},
                        outputs={'Out': sign}, attrs={})
        decay = block.create_var(dtype=param.dtype)
        block.append_op(type='scale', inputs={'X': sign},
                        outputs={'Out': decay},
                        attrs={'scale': self._regularization_coeff,
                               'bias': 0.0, 'bias_after_scale': True})
        block.append_op(type='elementwise_add',
                        inputs={'X': grad, 'Y': decay},
                        outputs={'Out': grad}, attrs={'axis': -1})
        return grad


def append_regularization_ops(parameters_and_grads, regularization=None):
    params_and_grads = []
    with op_role_guard(OpRole.Backward):
        for param, grad in parameters_and_grads:
            if grad is None:
                params_and_grads.append((param, grad))
                continue
            regularization_term = param.regularizer or regularization
            if regularization_term is not None:
                regularization_term(param, grad, grad.block)
            params_and_grads.append((param, grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
