"""AsyncExecutor: train straight from record files through the native C++
data pipeline.

Parity: reference python/paddle/fluid/async_executor.py + the C++
paddle/fluid/framework/async_executor.cc (multi-threaded file-fed training).
TPU-native redesign: the reference runs one CPU trainer thread per file, each
stepping its own program copy; on TPU there is ONE jitted train step, so the
parallelism that matters is host-side — the C++ BatchReader's reader/shuffle/
batch threads overlap file IO with the host-side FeedPrefetcher, which
stacks `steps_per_launch` batches into a superbatch and device_puts it while
the device runs the current launch (Executor.run_steps: K iterations fused
into one lax.scan executable = one dispatch through the device tunnel).
"""
import numpy as np

from .core.executor import Executor
from .core.framework import default_main_program
from .data_feeder import FeedPrefetcher
from .native import BatchReader, DataFeedDesc

__all__ = ['AsyncExecutor']


class AsyncExecutor(object):
    def __init__(self, place=None, run_mode=''):
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num=1,
            fetch=None, mode='', debug=False, fetch_every_n_steps=1,
            steps_per_launch=1):
        """Run `program` once over every batch the data feed yields.

        data_feed: a native.DataFeedDesc (slot names map batch fields to
        feed vars) or a ready BatchReader whose field order matches
        `feed_order` slots.  thread_num tunes the native prefetch depth
        AND the superbatch queue bound.  steps_per_launch=K fuses K
        iterations into one device launch.
        Returns the list of fetch results from the last step.
        """
        program = program or default_main_program()
        if isinstance(data_feed, DataFeedDesc):
            slot_names = [s[0] for s in data_feed.slots]
            reader = BatchReader(
                filelist or data_feed.paths, data_feed.batch_size,
                shuffle_capacity=data_feed.shuffle_capacity,
                seed=data_feed.seed, drop_last=data_feed.drop_last,
                prefetch=max(2, int(thread_num)))
        elif isinstance(data_feed, BatchReader):
            reader = data_feed
            slot_names = getattr(data_feed, 'slot_names', None)
            if slot_names is None:
                raise ValueError('BatchReader needs .slot_names to map '
                                 'fields to feed vars')
        else:
            raise TypeError('data_feed must be DataFeedDesc or BatchReader')

        fetch = fetch or []
        feeds = ({n: np.asarray(v) for n, v in zip(slot_names, fields)}
                 for fields in reader)
        prefetcher = FeedPrefetcher(feeds, steps=max(1, steps_per_launch),
                                    capacity=max(2, int(thread_num)))
        last = None
        step = 0
        try:
            for superbatch, k in prefetcher:
                out = self._exe.run_steps(program, feed_list=superbatch,
                                          steps=k, fetch_list=fetch)
                step += k
                if fetch:
                    # fetches come back stacked [k, ...]; the contract is
                    # the LAST step's values
                    last = [np.asarray(o[-1]) for o in out]
                    if debug and (step - 1) % max(1, fetch_every_n_steps) \
                            < k:
                        print('step %d: %s' %
                              (step - 1,
                               [np.asarray(o).ravel()[:4] for o in last]))
        finally:
            prefetcher.close()
        return last

    def config_distributed_nodes(self, *a, **k):
        raise NotImplementedError(
            'pserver-mode AsyncExecutor is obsoleted; use '
            'parallel.transpiler tpu_collective mode')
