#!/usr/bin/env python
"""Pod-scale survival soak: N lockstep trainer processes over one shared
checkpoint dir, killed and respawned mid-run, must converge with bitwise
resume parity and leave zero orphaned state.

Topology: each worker is one "host" of a pod — same model, same seeds,
same feed stream (lockstep replicas, the way data-parallel keeps params
identical on every host).  Workers write SHARDED checkpoints
(``CheckpointConfig(host_count=N)``): each host lands only its row-slice
(``arrays_<h>.npz``) into the serial's ``.parts`` staging dir and the
last one to land finalizes ``MANIFEST.json`` under ``ckpt.lock``.  Every
worker heartbeats through ``parallel/health.py``; a peer going silent
trips ``DeviceLossError`` → ``RecoveryPolicy`` rolls back to the last
good manifest and the worker exits ``RESTART_EXIT_CODE`` (75) so the
supervisor respawns the roster.

Supervisor scenario (the ci_smoke pod gate):

  ref     1-host uninterrupted run of the same stream → the reference
          loss curve every later segment must prefix-match BITWISE.
  wave 1  N workers; once >= 2 manifests have committed the supervisor
          SIGKILLs the last worker (no goodbye, partial shard left
          behind).  Survivors must detect the stale heartbeat, roll
          back, and exit 75 — not hang.
  wave 2  N workers respawned over the same dir (auto-resume); the last
          worker runs with ``PT_FAULT=device_loss:at=K`` — it stops
          heartbeating mid-run and HANGS (a wedged collective).
          Survivors trip, roll back, exit 75; the supervisor reaps the
          hung process.  The health trip must leave a flight-recorder
          dump (PT_FLIGHT_DIR).
  wave 3  the roster SHRINKS to N-1 workers (``host_count=N-1``):
          elastic restore re-slices the manifest onto the smaller
          roster (``ckpt.reshards`` > 0) and the run completes.

Asserts: every segment's losses == reference[start:start+len] (bitwise
resume parity, across kills, rosters, and reshards); the final loss
improved on the first (convergence); rollbacks > 0 and device-loss
trips > 0; zero processes needed killing beyond the two deliberate
victims (zero hung collectives); zero ``.tmp_ckpt_*`` / ``*.parts``
left in the checkpoint dir; a ``health_trip`` flight dump exists.

Prints one JSON verdict line, fault_soak-style.
"""
import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _harness  # noqa: E402 - shared stage/watchdog/JSON-tail contract


# --------------------------------------------------------------- worker
def worker_main(args):
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.parallel.health import (HealthConfig, HealthMonitor,
                                            DeviceLossError,
                                            RESTART_EXIT_CODE)
    from paddle_tpu.train import (CheckpointConfig, Checkpointer,
                                  RecoveryPolicy)

    _flight.install()   # an uncaught crash still leaves a postmortem

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 17
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, 0.2)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    main_prog.set_amp(True)

    def feed_at(i):
        rng = np.random.RandomState(1000 + i)
        return {'x': rng.rand(8, 8).astype('float32'),
                'lbl': rng.randint(0, 4, (8, 1)).astype('int64')}

    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    ck = Checkpointer(
        CheckpointConfig(args.ckpt, step_interval=1, max_num_checkpoints=3,
                         host_id=args.host, host_count=args.hosts,
                         sharded=True),
        exe, main_prog, scope=scope)
    hm = HealthMonitor(HealthConfig(args.health, host_id=args.host,
                                    host_count=args.hosts,
                                    timeout_s=args.health_timeout))
    policy = RecoveryPolicy(ck, max_retries=4)

    def report(losses, start, restart):
        c = obs.counters()
        rec = {'host': args.host, 'hosts': args.hosts, 'pid': os.getpid(),
               'start': start, 'losses': losses, 'restart': restart,
               'counters': obs.telemetry_snapshot(
                   'resilience', snapshot=c)['counters']}
        print(json.dumps(rec))
        sys.stdout.flush()

    losses = []
    start = 0
    try:
        with fluid.scope_guard(scope):
            meta = ck.restore()
            start = meta['step_id'] + 1 if meta else 0
            if args.expect_resume and start < 1:
                sys.exit('pod_soak worker %d: --expect-resume but no '
                         'valid checkpoint in %s' % (args.host, args.ckpt))
            if meta is None:
                exe.run(startup)
                # restore point BEFORE any step: recovery can roll back
                # even a first-step loss
                ck.save(0, -1)
                ck.wait()
            # compile BEFORE the first heartbeat: the cold trace+compile
            # takes seconds, and a beat followed by a multi-second pause
            # reads exactly like a lost device to every peer
            exe.prepare(main_prog, feed=feed_at(start), fetch_list=[loss])
            for i in range(start, args.steps):
                if not hm.beat(i):
                    # device_loss injected: a lost device does not exit —
                    # it WEDGES.  The supervisor must reap us; peers must
                    # detect the silence.
                    time.sleep(3600)

                def launch(i=i):
                    hm.check(i)
                    return exe.run(main_prog, feed=feed_at(i),
                                   fetch_list=[loss])
                out = policy.run(launch)
                if out is None:
                    continue   # divergence rollback (not armed here)
                losses.append(float(np.asarray(out[0]).ravel()[0]))
                ck.maybe_save(0, i)
                if args.step_delay:
                    time.sleep(args.step_delay)
            hm.mark_done()
            ck.wait()
    except DeviceLossError:
        # policy already rolled the scope back to the last good manifest;
        # hand control to the supervisor for a restart on whatever
        # roster survives
        report(losses, start, restart=True)
        return RESTART_EXIT_CODE
    report(losses, start, restart=False)
    return 0


# ----------------------------------------------------------- supervisor
class Wave(object):
    def __init__(self, name):
        self.name = name
        self.results = []     # (host, rc, parsed-json-or-None)
        self.reaped = []      # hosts the supervisor had to SIGKILL


def _spawn(args, host, hosts, health_dir, extra_env=None, step_delay=0.0,
           expect_resume=False):
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env['PT_CACHE'] = '0'
    env.pop('PT_FAULT', None)
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, os.path.abspath(__file__), '--worker',
           '--ckpt', args.ckpt, '--health', health_dir,
           '--host', str(host), '--hosts', str(hosts),
           '--steps', str(args.steps),
           '--step-delay', str(step_delay),
           '--health-timeout', str(args.health_timeout)]
    if expect_resume:
        cmd.append('--expect-resume')
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)


def _finish(proc, timeout):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, None
    rec = None
    for line in reversed((out or '').strip().splitlines()):
        if line.startswith('{'):
            try:
                rec = json.loads(line)
            except ValueError:
                pass
            break
    return proc.returncode, rec


def _manifests(ckpt_dir):
    return len(glob.glob(os.path.join(ckpt_dir, 'checkpoint_*',
                                      '_SUCCESS')))


def _orphans(ckpt_dir):
    return (glob.glob(os.path.join(ckpt_dir, '.tmp_ckpt_*')) +
            glob.glob(os.path.join(ckpt_dir, '*.parts')))


def supervisor_main(args):
    os.makedirs(args.dir, exist_ok=True)
    args.ckpt = os.path.join(args.dir, 'ckpt')
    flight_dir = os.path.join(args.dir, 'flight')
    os.environ['PT_FLIGHT_DIR'] = flight_dir
    fails = []

    def check(cond, msg):
        if not cond:
            fails.append(msg)
            print('pod_soak: FAIL %s' % msg, file=sys.stderr)

    # ---- reference: 1 uninterrupted host, same stream --------------
    _harness.stage('reference')
    ref_args = argparse.Namespace(**vars(args))
    ref_args.ckpt = os.path.join(args.dir, 'ref_ckpt')
    p = _spawn(ref_args, host=0, hosts=1,
               health_dir=os.path.join(args.dir, 'ref_health'))
    rc, ref = _finish(p, args.wave_timeout)
    if rc != 0 or not ref:
        sys.exit('pod_soak: reference run failed (rc=%r)' % (rc,))
    R = ref['losses']
    print('pod_soak: reference %d steps, loss %.4f -> %.4f'
          % (len(R), R[0], R[-1]))
    check(len(R) == args.steps and all(
        isinstance(v, float) and v == v and abs(v) != float('inf')
        for v in R), 'reference run incomplete or non-finite')

    waves = []
    segments = [ref]

    def run_wave(name, hosts, fault_host_env=None, step_delay=None,
                 kill_after_manifests=None, expect_resume=False,
                 wedge_host=None):
        wave = Wave(name)
        waves.append(wave)
        _harness.stage('wave_%s' % name)
        health_dir = os.path.join(args.dir, 'health_%s' % name)
        delay = args.step_delay if step_delay is None else step_delay
        procs = {}
        for h in range(hosts):
            extra = fault_host_env if (fault_host_env and
                                       h == hosts - 1) else None
            procs[h] = _spawn(args, host=h, hosts=hosts,
                              health_dir=health_dir, extra_env=extra,
                              step_delay=delay,
                              expect_resume=expect_resume)
        deadline = time.time() + args.wave_timeout
        if kill_after_manifests is not None:
            while _manifests(args.ckpt) < kill_after_manifests:
                if time.time() > deadline:
                    for pr in procs.values():
                        pr.kill()
                    sys.exit('pod_soak: wave %s never reached %d '
                             'manifests' % (name, kill_after_manifests))
                time.sleep(0.05)
            victim = hosts - 1
            procs[victim].send_signal(signal.SIGKILL)
            print('pod_soak: wave %s SIGKILLed host %d at %d manifests'
                  % (name, victim, _manifests(args.ckpt)))
        pending = dict(procs)
        wedge_grace = None
        while pending:
            now = time.time()
            for h in list(pending):
                if pending[h].poll() is None:
                    continue
                rc, rec = _finish(pending.pop(h), 10.0)
                wave.results.append((h, rc, rec))
                if rec:
                    segments.append(rec)
            if not pending:
                break
            if set(pending) == {wedge_host} and wedge_grace is None:
                # every peer has exited: the deliberately-wedged
                # device_loss worker is the only process allowed to
                # need reaping — give it one last detection window
                wedge_grace = now + max(2.0, 4 * args.health_timeout)
            if now > deadline or (wedge_grace is not None and
                                  now > wedge_grace):
                # anything ELSE still running here is a hung collective —
                # the exact failure this layer exists to prevent
                for h, pr in pending.items():
                    pr.kill()
                    pr.communicate()
                    wave.reaped.append(h)
                    print('pod_soak: wave %s reaped hung host %d'
                          % (name, h))
                pending.clear()
            time.sleep(0.05)
        return wave

    # wave 1: hard SIGKILL mid-run; survivors must trip + roll back
    w1 = run_wave('gen0', hosts=args.workers, kill_after_manifests=2)
    survivors = [(h, rc, rec) for h, rc, rec in w1.results
                 if rc not in (None, -9)]
    check(len(survivors) == args.workers - 1,
          'wave gen0: expected %d survivors, got %d'
          % (args.workers - 1, len(survivors)))
    for h, rc, rec in survivors:
        check(rc == 75, 'wave gen0 host %d: expected exit 75 (rollback + '
              'restart request), got %r' % (h, rc))
    check(not w1.reaped, 'wave gen0: hung worker(s) %r' % w1.reaped)

    # wave 2: injected device loss — the victim WEDGES instead of dying
    loss_at = max(2, args.device_loss_at)
    w2 = run_wave('gen1', hosts=args.workers,
                  fault_host_env={'PT_FAULT': 'device_loss:at=%d' % loss_at},
                  expect_resume=True, wedge_host=args.workers - 1)
    survivors2 = [(h, rc, rec) for h, rc, rec in w2.results]
    check(w2.reaped == [args.workers - 1],
          'wave gen1: expected exactly the wedged host %d reaped, got %r'
          % (args.workers - 1, w2.reaped))
    check(len(survivors2) == args.workers - 1,
          'wave gen1: expected %d survivors, got %d'
          % (args.workers - 1, len(survivors2)))
    for h, rc, rec in survivors2:
        check(rc == 75, 'wave gen1 host %d: expected exit 75, got %r'
              % (h, rc))
        if rec:
            check(rec['counters'].get('health.lost_hosts', 0) >= 1,
                  'wave gen1 host %d: no health.lost_hosts trip' % h)
            check(rec['counters'].get('recovery.device_loss', 0) >= 1,
                  'wave gen1 host %d: no recovery.device_loss rollback' % h)

    # wave 3: the roster SHRINKS — elastic restore onto fewer hosts
    w3 = run_wave('gen2', hosts=args.workers - 1, step_delay=0.0,
                  expect_resume=True)
    check(not w3.reaped, 'wave gen2: hung worker(s) %r' % w3.reaped)
    check(len(w3.results) == args.workers - 1 and
          all(rc == 0 for _, rc, _ in w3.results),
          'wave gen2: shrunken roster did not complete cleanly: %r'
          % [(h, rc) for h, rc, _ in w3.results])
    for h, rc, rec in w3.results:
        if not rec:
            continue
        if args.expect_resume:
            check(rec['start'] > 0,
                  'wave gen2 host %d: did not resume (start=0)' % h)
        if args.expect_reshard:
            check(rec['counters'].get('ckpt.reshards', 0) >= 1,
                  'wave gen2 host %d: no ckpt.reshards — restore did not '
                  'cross the roster change' % h)

    # ---- cross-cutting asserts -------------------------------------
    _harness.stage('audit')
    # bitwise resume parity: EVERY segment (all waves, all hosts) must
    # prefix-match the uninterrupted reference from its start step
    for seg in segments[1:]:
        s, got = seg['start'], seg['losses']
        want = R[s:s + len(got)]
        check(got == want,
              'host %d (hosts=%d, start=%d): losses diverge from the '
              'reference stream' % (seg['host'], seg['hosts'], s))
    rollbacks = sum(seg['counters'].get('recovery.rollbacks', 0)
                    for seg in segments[1:])
    check(rollbacks > 0, 'no rollbacks anywhere — the kills never '
          'exercised recovery')
    finals = [seg for seg in segments[1:] if not seg.get('restart')]
    check(all(seg['start'] + len(seg['losses']) == args.steps
              for seg in finals) and finals,
          'final segment(s) did not complete the run: %r'
          % [(seg['host'], seg['start'], len(seg['losses']))
             for seg in finals])
    orphans = _orphans(args.ckpt)
    check(not orphans, 'orphaned checkpoint state left behind: %r'
          % orphans)
    dumps = glob.glob(os.path.join(flight_dir, '*health_trip*.json'))
    check(len(dumps) >= 1, 'no health_trip flight dump in %s' % flight_dir)

    verdict = {
        'ok': not fails,
        'reference_steps': len(R),
        'segments': len(segments) - 1,
        'rollbacks': rollbacks,
        'manifests': _manifests(args.ckpt),
        'reaped': {w.name: w.reaped for w in waves},
        'health_trip_dumps': len(dumps),
        'failures': fails,
    }
    print(json.dumps(verdict))
    from paddle_tpu.observability import perflab
    perflab.maybe_ledger(
        'pod_soak',
        {'failures': len(fails),
         'segments': verdict['segments'],
         'rollbacks': rollbacks,
         'manifests': verdict['manifests']})
    return 0 if not fails else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--worker', action='store_true')
    ap.add_argument('--workers', type=int, default=2,
                    help='pod size (supervisor mode)')
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--dir', default=None,
                    help='supervisor workdir (ckpt + health + flight)')
    ap.add_argument('--ckpt', default=None)
    ap.add_argument('--health', default=None)
    ap.add_argument('--host', type=int, default=0)
    ap.add_argument('--hosts', type=int, default=1)
    ap.add_argument('--step-delay', type=float, default=0.15,
                    help='per-step sleep so staleness detection lands '
                         'mid-run, not post-run')
    ap.add_argument('--health-timeout', type=float, default=1.5)
    ap.add_argument('--device-loss-at', type=int, default=None,
                    help='step the wave-2 victim stops heartbeating at '
                         '(default steps//2)')
    ap.add_argument('--wave-timeout', type=float, default=240.0)
    ap.add_argument('--expect-resume', action='store_true')
    ap.add_argument('--expect-reshard', action='store_true')
    args = ap.parse_args()
    if args.device_loss_at is None:
        args.device_loss_at = args.steps // 2
    if args.worker:
        if not (args.ckpt and args.health):
            sys.exit('pod_soak --worker needs --ckpt and --health')
        return worker_main(args)
    if args.workers < 2:
        sys.exit('pod_soak needs --workers >= 2 (the scenario kills one)')
    if args.dir is None:
        import tempfile
        args.dir = tempfile.mkdtemp(prefix='pt_pod_soak.')
    return supervisor_main(args)


if __name__ == '__main__':
    _harness.set_tool('POD_SOAK')
    _harness.main_guard(main, watchdog_env='PT_SOAK_WATCHDOG_S',
                        flight_tag='pod_soak.watchdog')
