#!/usr/bin/env python
"""The continuous performance lab: a scenario-matrix bench runner with
an append-only ledger and a baseline regression gate.

Exactly one way to produce a perf number in this repo (ROADMAP item 5):

  run      execute the scenario matrix, each scenario in a
           SUBPROCESS-ISOLATED child with a hard budget — one hang
           kills one scenario, not the round — and append one
           schema-validated, provenance-stamped record per scenario to
           the ledger (PERF_HISTORY.jsonl by default).
  compare  diff the newest ledger record per scenario against the
           committed PERF_BASELINE.json: deterministic counters are
           zero-tolerance, timings are noise-bounded best-of-K, and a
           cpu-fallback record vs a TPU baseline is a structured
           REFUSAL, not a pass.
  check    assert every requested scenario has a schema-valid,
           non-error, provenance-complete ledger record (the ci gate).
  bless    write the newest ledger records out as the new baseline.
  list     print the scenario registry.
  probe    one-shot diagnostic harnesses (absorbed tools/measure.py).
  models   the reference model-matrix benchmark CLI (absorbed
           tools/fluid_benchmark.py).

Scenarios (geometry via the BENCH_* shrink knobs, see docs/perflab.md):

  train_transformer  fused train-step throughput (tokens/s, MFU) via
                     run_steps K-launches — the bench.py headline
  train_resnet       ResNet training throughput (img/s)
  decode_stream      GenerationEngine streaming decode: tokens/s/chip
                     + TTFT/ITL p99 under open-loop load
  pod_parallel       all-reduce bandwidth over the local mesh + 2-host
                     lockstep scaling (subprocess workers)
  fused_adam_micro   the kernelgen tier's headline op: ms/step of the
                     fused-Adam update

Record + comparison semantics live in
paddle_tpu/observability/perflab.py; the per-scenario metric schemas in
observability/export.py (SCHEMA['perflab.*']).
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _harness  # noqa: E402 - shared stage/watchdog/probe machinery

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_LEDGER = os.path.join(REPO_ROOT, 'PERF_HISTORY.jsonl')
DEFAULT_BASELINE = os.path.join(REPO_ROOT, 'PERF_BASELINE.json')

# the scenario matrix `run` executes by default, in order (the ledger
# bridge sections — perflab.bench etc. — are written by those tools
# themselves, not by the lab)
MATRIX = ('train_transformer', 'train_resnet', 'decode_stream',
          'pod_parallel', 'fused_adam_micro')


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def _on_tpu():
    import jax
    return jax.default_backend() not in ('cpu',)


def _best_of(fn, k):
    """Run ``fn`` k times; return (best implied by caller, samples).
    The caller picks best via max/min on the samples."""
    return [fn() for _ in range(max(1, k))]


# ------------------------------------------------------------ scenarios
def scenario_train_transformer(best_of):
    """The bench.py headline, lab-sized: fused run_steps launches of a
    transformer train step, best-of-K tokens/s, self-labeling counters
    snapshotted AFTER warmup so a retrace during the timed loop is a
    counter regression, not silent pollution."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.core import passes as pt_passes
    from paddle_tpu.models import transformer as tr
    from bench import peak_flops

    on_tpu = _on_tpu()
    B = _env_int('BENCH_B', 32 if on_tpu else 4)
    T = _env_int('BENCH_T', 256 if on_tpu else 64)
    vocab = _env_int('BENCH_VOCAB', 32000)
    n_layer = _env_int('BENCH_LAYERS', 6)
    n_head = _env_int('BENCH_HEADS', 8)
    d_model = _env_int('BENCH_DMODEL', 512)
    d_inner = _env_int('BENCH_DINNER', 2048)
    K = max(2, _env_int('BENCH_STEPS_PER_LAUNCH', 8))
    launches = _env_int('PERFLAB_LAUNCHES', 3 if on_tpu else 2)

    _harness.stage('build')
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=vocab, trg_vocab=vocab, max_len=T,
                           n_layer=n_layer, n_head=n_head, d_model=d_model,
                           d_inner=d_inner, dropout=0.0, use_flash=True)
    main_prog.set_amp(True)
    exe, scope = fluid.Executor(), fluid.Scope()
    rng = np.random.RandomState(0)
    feed = tr.synthetic_batch(rng, B, T, vocab)
    tokens_per_step = float(np.sum(1.0 - feed['trg_pad']))
    n_params = sum(int(np.prod(v.shape)) for v in
                   main_prog.global_block().all_parameters() if v.shape)
    n_matmul = n_params - sum(
        int(np.prod(v.shape)) for v in
        main_prog.global_block().all_parameters()
        if v.shape and v.name.endswith('_emb'))

    with fluid.scope_guard(scope):
        _harness.stage('warmup')
        exe.run(startup)
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(3):
            loss, = exe.run(main_prog, feed=feed, fetch_list=[out['loss']])
        np.asarray(loss)
        superfeed = {k: jnp.stack([v] * K) for k, v in feed.items()}
        exe.run_steps(main_prog, feed_list=superfeed, steps=K,
                      fetch_list=[out['loss']])
        _harness.stage('measure')
        c0 = obs.counters()
        blocked0 = float(c0.get('executor.host_blocked_s') or 0)

        def trial():
            t0 = time.perf_counter()
            for _ in range(launches):
                losses, = exe.run_steps(main_prog, feed_list=superfeed,
                                        steps=K, fetch_list=[out['loss']],
                                        return_numpy=False)
            np.asarray(losses)
            return launches * K * tokens_per_step / \
                (time.perf_counter() - t0)

        samples = _best_of(trial, best_of)
        c1 = obs.counters()

    tps = max(samples)
    attn_layers = 3 * n_layer
    flops_per_token = 6.0 * n_matmul + 12.0 * T * d_model * attn_layers
    peak = peak_flops(str(jax.devices()[0].device_kind)) if on_tpu else None
    mfu = round(flops_per_token * tps / peak, 4) if peak else None
    raw_ops = sum(len(b.ops) for b in main_prog.blocks)
    _, opt_stats = pt_passes.maybe_optimize(main_prog, (out['loss'].name,))
    metrics = {
        'program_op_count_opt': int(opt_stats['op_count_opt']
                                    if opt_stats else raw_ops),
        'compiles_after_warmup': int((c1.get('executor.compiles') or 0) -
                                     (c0.get('executor.compiles') or 0)),
        'retraces': int((c1.get('executor.retraces') or 0) -
                        (c0.get('executor.retraces') or 0)),
        'kernel_fallbacks': int(c1.get('kernel.fallbacks') or 0),
        'kernelgen_fallbacks': int(c1.get('kernelgen.fallbacks') or 0),
        'emitter_fallbacks': int(c1.get('emitter.fallbacks') or 0),
        'tokens_per_s': round(tps, 1),
        'mfu': mfu,
        'host_blocked_s': round(float(
            (c1.get('executor.host_blocked_s') or 0)) - blocked0, 3),
        'params_m': round(n_params / 1e6, 2),
        'batch': B, 'seq': T, 'steps_per_launch': K,
    }
    config = {'batch': B, 'seq': T, 'vocab': vocab, 'layers': n_layer,
              'heads': n_head, 'd_model': d_model, 'd_inner': d_inner,
              'steps_per_launch': K, 'launches': launches}
    return metrics, {'tokens_per_s': [round(s, 1) for s in samples]}, config


def scenario_train_resnet(best_of):
    import jax
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.models import resnet
    from bench import (peak_flops, RESNET50_TRAIN_FLOPS_PER_IMAGE)

    on_tpu = _on_tpu()
    B = _env_int('BENCH_RESNET_B', 128 if on_tpu else 2)
    depth = _env_int('BENCH_RESNET_DEPTH', 50)
    data_set = os.environ.get('BENCH_RESNET_SET',
                              'imagenet' if on_tpu else 'cifar10')
    side = 224 if data_set == 'imagenet' else 32
    classes = 1000 if data_set == 'imagenet' else 10
    steps = _env_int('PERFLAB_RESNET_STEPS', 20 if on_tpu else 3)

    _harness.stage('build')
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out = resnet.build(data_shape=(3, side, side),
                               class_dim=classes, depth=depth, lr=0.1,
                               data_set=data_set)
    main_prog.set_amp(True)
    exe, scope = fluid.Executor(), fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {'data': rng.rand(B, 3, side, side).astype('float32'),
            'label': rng.randint(0, classes, (B, 1)).astype('int64')}
    with fluid.scope_guard(scope):
        _harness.stage('warmup')
        exe.run(startup)
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(3):
            loss, = exe.run(main_prog, feed=feed, fetch_list=[out['loss']])
        np.asarray(loss)
        _harness.stage('measure')
        c0 = obs.counters()

        def trial():
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, = exe.run(main_prog, feed=feed,
                                fetch_list=[out['loss']],
                                return_numpy=False)
            np.asarray(loss)
            return steps * B / (time.perf_counter() - t0)

        samples = _best_of(trial, best_of)
        c1 = obs.counters()

    ips = max(samples)
    peak = peak_flops(str(jax.devices()[0].device_kind)) if on_tpu else None
    mfu = (round(RESNET50_TRAIN_FLOPS_PER_IMAGE * ips / peak, 4)
           if peak and depth == 50 else None)
    metrics = {
        'compiles_after_warmup': int((c1.get('executor.compiles') or 0) -
                                     (c0.get('executor.compiles') or 0)),
        'retraces': int((c1.get('executor.retraces') or 0) -
                        (c0.get('executor.retraces') or 0)),
        'kernel_fallbacks': int(c1.get('kernel.fallbacks') or 0),
        'emitter_fallbacks': int(c1.get('emitter.fallbacks') or 0),
        'images_per_s': round(ips, 1),
        'mfu': mfu,
        'batch': B, 'depth': depth,
    }
    config = {'batch': B, 'depth': depth, 'data_set': data_set,
              'steps': steps}
    return metrics, {'images_per_s': [round(s, 1) for s in samples]}, config


def scenario_decode_stream(best_of):
    """Streaming generation through the GenerationEngine over the PAGED
    KV pool: open-loop token-stream load under a FIXED page-budget
    (int8-quantized pages, shared-prefix caching, speculative decode
    all on), tokens/s/chip from the generation.tokens counter, TTFT/ITL
    p99 from the serving histograms, and the serving-density headline —
    peak concurrent streams the budget sustained at held SLOs
    (``streams_at_slo``) against the streams a dense PR-11 layout
    could have reserved in the same bytes (``density_x_vs_dense``)."""
    import threading

    import numpy as np
    import paddle_tpu.observability as obs
    from paddle_tpu.serving.engine import ServingConfig
    from paddle_tpu.serving.generation import (CacheConfig, DecodeRuntime,
                                               GenerationConfig,
                                               GenerationEngine)
    from paddle_tpu.serving.generation.decode import random_weights

    requests = _env_int('PERFLAB_DECODE_REQUESTS', 24)
    slots = _env_int('PERFLAB_DECODE_SLOTS', 10)
    K = _env_int('PERFLAB_DECODE_WINDOW', 4)
    budget = _env_int('PERFLAB_DECODE_KV_BUDGET', 16384)
    page_len = _env_int('PERFLAB_DECODE_PAGE_LEN', 4)
    quant = os.environ.get('PERFLAB_DECODE_KV_QUANT', 'int8')

    _harness.stage('build')
    cfg = dict(vocab=128, d_model=32, n_layer=2, n_head=4, n_kv_head=2,
               d_ffn=64, theta=10000.0, max_len=32)
    w = random_weights(cfg, seed=0)
    geom = CacheConfig(slots=slots, layers=cfg['n_layer'],
                       kv_heads=cfg['n_kv_head'], max_len=cfg['max_len'],
                       head_dim=cfg['d_model'] // cfg['n_head'],
                       page_len=page_len, quant=quant)
    # fixed byte budget -> pool depth; the same budget under the dense
    # PR-11 layout (one f32 max_len strip per stream) is the density
    # denominator
    pages = max(2, budget // geom.page_bytes() + 1)   # +1: garbage page
    dense_streams = max(1, budget // geom.dense_slot_bytes())
    rt = DecodeRuntime(w, cfg, slots=slots, prefill_chunk=4,
                       page_len=page_len, pages=pages, kv_quant=quant,
                       prefix_cache=True)
    engine = GenerationEngine(
        rt, config=ServingConfig(max_queue=max(64, 2 * requests),
                                 drain_timeout_s=60.0),
        gen_config=GenerationConfig(decode_window=K,
                                    speculative=True)).start()
    _harness.stage('warmup')
    rt.warmup(steps=K, speculative=True)
    engine.generate([3, 1, 4, 1, 5], max_new=4).result(120)
    c0 = obs.counters()
    compiles0 = int(c0.get('generation.compiles') or 0)
    tokens0 = int(c0.get('generation.tokens') or 0)

    _harness.stage('measure')
    # every prompt shares one FULL page of system prefix (prefix-cache
    # hits after the first stream publishes it) plus a distinct tail;
    # per-stream page demand stays within slots * worst-case even with
    # zero sharing, so the budget never kills a stream mid-flight
    shared = [(3 + j) % (cfg['vocab'] - 1) + 1 for j in range(page_len)]
    tails = (1, 2, 3)
    peak = [0]
    done = threading.Event()

    def poll_peak():
        while not done.is_set():
            peak[0] = max(peak[0], rt.allocator.in_use())
            time.sleep(0.001)

    poller = threading.Thread(target=poll_peak, daemon=True)
    poller.start()
    t0 = time.perf_counter()
    streams = []
    for i in range(requests):
        n = tails[i % len(tails)]
        prompt = shared + [(7 * i + j) % (cfg['vocab'] - 1) + 1
                           for j in range(n)]
        streams.append(engine.generate(
            prompt, max_new=6,
            temperature=0.8 if i % 3 else 0.0,
            top_k=5 if i % 3 else 0, seed=i, timeout_s=120.0))
    ok = failed = 0
    for s in streams:
        try:
            res = s.result(120)
            ok += 1 if res.ok else 0
            failed += 0 if res.ok else 1
        except Exception:
            failed += 1
    dt = time.perf_counter() - t0
    done.set()
    poller.join(1.0)
    engine.stop()

    _harness.stage('audit')
    c1 = obs.counters()
    tel = obs.telemetry_snapshot('serving')
    new_tokens = int(c1.get('generation.tokens') or 0) - tokens0
    tps = new_tokens / dt if dt > 0 else 0.0
    if rt.prefix is not None:
        rt.prefix.reset()          # cached pages are holds, not leaks
    pages_leaked = int(rt.pool.in_use())
    slots_leaked = int(rt.slots - rt.free_slots())
    slo_held = (failed == 0 and ok == requests
                and int(tel['deadlocks']) == 0 and slots_leaked == 0
                and pages_leaked == 0)
    streams_at_slo = int(peak[0]) if slo_held else 0

    def fin(v):
        return float(v) if v is not None and np.isfinite(v) else None

    metrics = {
        'compiles_after_warmup': int(c1.get('generation.compiles') or 0) -
        compiles0,
        'deadlocks': int(tel['deadlocks']),
        'kv_slots_leaked': slots_leaked,
        'kv_pages_leaked': pages_leaked,
        'streams_failed': failed,
        'streams_at_slo': streams_at_slo,
        'density_x_vs_dense': streams_at_slo // dense_streams,
        'tokens_per_s_per_chip': round(tps, 1),
        'ttft_p99_ms': fin(tel['ttft_p99_ms']),
        'itl_p99_ms': fin(tel['itl_p99_ms']),
        'requests': requests,
        'streams_ok': ok,
    }
    config = {'requests': requests, 'slots': slots, 'decode_window': K,
              'model': cfg, 'page_len': page_len, 'pages': pages,
              'kv_quant': quant, 'kv_budget_bytes': budget,
              'dense_streams_in_budget': dense_streams,
              'speculative': True, 'prefix_cache': True}
    # one open-loop pass is the sample — TTFT/ITL p99 already aggregate
    # per-token noise, and re-running would double-count warm KV state
    return metrics, {'tokens_per_s_per_chip': [round(tps, 1)]}, config


def _pod_shard_round():
    """Replicated-vs-ZeRO-sharded in one round on the local mesh: per-
    device persistable HBM (via addressable_shards, not the cost model)
    plus the shard pass's explicit-collective accounting.  Returns {}
    below 2 devices — the schema keys then stay absent, which the gate
    treats as not-measured rather than regressed."""
    import jax
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core import passes
    from paddle_tpu.parallel.mesh import make_mesh

    if jax.local_device_count() < 2:
        return {}
    mesh = make_mesh(data=2, devices=jax.devices()[:2])

    def build():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data('ps_x', shape=[64], dtype='float32')
                h = fluid.layers.fc(x, size=64, act='relu')
                y = fluid.layers.fc(h, size=64)
                loss = fluid.layers.reduce_mean(y * y)
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        main_prog.set_mesh_axes(mesh)
        x.sharding = (None, None)   # replicated feed: bitwise comparable
        return main_prog, startup, loss

    def dev0_bytes(scope, persist):
        total = 0
        for n in persist:
            arr = scope.vars.get(n)
            if arr is None or not hasattr(arr, 'addressable_shards'):
                continue
            total += sum(s.data.nbytes for s in arr.addressable_shards
                         if s.device == jax.devices()[0])
        return total

    feed = {'ps_x': np.random.RandomState(0).rand(16, 64).astype('float32')}
    out = {}
    for label, shard_on in (('replicated', '0'), ('sharded', '1')):
        old = os.environ.get('PT_SHARD')
        os.environ['PT_SHARD'] = shard_on
        try:
            main_prog, startup, loss = build()
            exe, scope = fluid.Executor(mesh=mesh), fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                for _ in range(3):
                    exe.run(main_prog, feed=feed, fetch_list=[loss])
                persist = [v.name for v in main_prog.list_vars()
                           if v.persistable]
                out['hbm_params_bytes_%s' % label] = \
                    dev0_bytes(scope, persist)
            if shard_on == '1':
                _, stats = passes.optimize_program(main_prog, (loss.name,))
                sh = stats['passes'].get('shard') or {}
                out['reshards_inserted'] = int(
                    sh.get('reshards_inserted') or 0)
                out['collective_bytes'] = int(
                    sh.get('collective_bytes') or 0)
        finally:
            if old is None:
                os.environ.pop('PT_SHARD', None)
            else:
                os.environ['PT_SHARD'] = old
    rep = out.get('hbm_params_bytes_replicated') or 0
    shd = out.get('hbm_params_bytes_sharded') or 0
    out['hbm_sharded_ratio'] = round(shd / rep, 3) if rep else None
    return out


def scenario_pod_parallel(best_of):
    """Pod-story plumbing: psum bus bandwidth over the local mesh (null
    single-device), the shard pass's replicated-vs-sharded HBM round,
    and 2-worker lockstep throughput scaling via subprocess workers —
    the shape the real pod gate grows into."""
    import jax
    from bench import allreduce_bw_gbps

    steps = _env_int('PERFLAB_POD_STEPS', 8)
    _harness.stage('shard_round')
    try:
        shard_metrics = _pod_shard_round()
    except Exception as e:  # noqa: BLE001 - diagnostic-only path
        print('PERFLAB: shard round failed: %s' % e, file=sys.stderr)
        shard_metrics = {}
    _harness.stage('allreduce')
    devices = jax.local_device_count()
    try:
        bw = allreduce_bw_gbps(n_iters=5, nbytes=8 * 1024 * 1024)
    except Exception as e:  # noqa: BLE001 - diagnostic-only path
        print('PERFLAB: allreduce microbench failed: %s' % e,
              file=sys.stderr)
        bw = None

    def spawn():
        env = dict(os.environ)
        # workers measure host-side step throughput; keep their device
        # view simple regardless of this child's forced multi-device one
        env.pop('XLA_FLAGS', None)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), 'podworker',
             '--steps', str(steps)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)

    def finish(proc, timeout):
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return None
        for line in reversed((out or '').strip().splitlines()):
            if line.startswith('{'):
                try:
                    rec = json.loads(line)
                except ValueError:
                    return None
                return rec if proc.returncode == 0 else None
        return None

    budget = float(os.environ.get('PERFLAB_POD_WORKER_S', '240'))
    _harness.stage('single_worker')
    r1 = finish(spawn(), budget)
    _harness.stage('dual_worker')
    procs = [spawn(), spawn()]
    r2 = [finish(p, budget) for p in procs]

    completed = (1 if r1 else 0) + sum(1 for r in r2 if r)
    failures = 3 - completed
    single = r1['steps_per_s'] if r1 else None
    aggregate = (sum(r['steps_per_s'] for r in r2 if r)
                 if all(r2) else None)
    scaling = (round(aggregate / single, 3)
               if single and aggregate else None)
    metrics = {
        'workers_completed': completed,
        'worker_failures': failures,
        'allreduce_gbps': round(bw, 2) if bw is not None else None,
        'steps_per_s_1worker': round(single, 2) if single else None,
        'scaling_2worker_x': scaling,
        'devices': devices,
    }
    metrics.update(shard_metrics)
    config = {'steps': steps, 'workers': 2}
    return metrics, {}, config


def scenario_fused_adam_micro(best_of):
    """The kernelgen tier's headline op: ms/step of the fused-Adam
    update (ONE generated kernel when PT_KERNELGEN=1), with the tier's
    own counters as the zero-tolerance gate."""
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs

    steps = _env_int('PERFLAB_ADAM_STEPS', 20)
    _harness.stage('build')
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('fa_x', shape=[64], dtype='float32')
            h = fluid.layers.fc(x, size=64, act='relu')
            y = fluid.layers.fc(h, size=64)
            loss = fluid.layers.reduce_mean(y * y)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe, scope = fluid.Executor(), fluid.Scope()
    feed = {'fa_x': np.random.RandomState(0).rand(32, 64).astype('float32')}
    n_params = sum(int(np.prod(v.shape)) for v in
                   main_prog.global_block().all_parameters() if v.shape)
    with fluid.scope_guard(scope):
        _harness.stage('warmup')
        exe.run(startup)
        for _ in range(3):
            exe.run(main_prog, feed=feed, fetch_list=[loss])
        _harness.stage('measure')
        c0 = obs.counters()

        def trial():
            t0 = time.perf_counter()
            for _ in range(steps):
                exe.run(main_prog, feed=feed, fetch_list=[loss],
                        return_numpy=False)
            lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
            np.asarray(lv)
            return (time.perf_counter() - t0) / (steps + 1) * 1000.0

        samples = _best_of(trial, best_of)
        c1 = obs.counters()

    metrics = {
        'kernelgen_ops': int(c1.get('kernelgen.ops') or 0),
        'kernelgen_fallbacks': int(c1.get('kernelgen.fallbacks') or 0),
        'retraces': int((c1.get('executor.retraces') or 0) -
                        (c0.get('executor.retraces') or 0)),
        'fused_adam_ms': round(min(samples), 3),
        'params': n_params,
    }
    config = {'steps': steps}
    return metrics, {'fused_adam_ms': [round(s, 3) for s in samples]}, config


SCENARIOS = {
    'train_transformer': scenario_train_transformer,
    'train_resnet': scenario_train_resnet,
    'decode_stream': scenario_decode_stream,
    'pod_parallel': scenario_pod_parallel,
    'fused_adam_micro': scenario_fused_adam_micro,
}

# test-only scenarios (tests/test_perflab.py): a child that hangs past
# its budget and a near-instant one — enabled explicitly so the real
# matrix can't pick them up
if os.environ.get('PERFLAB_TEST_SCENARIOS') == '1':
    from paddle_tpu.observability.export import SCHEMA as _SCHEMA

    _SCHEMA.setdefault('perflab._quick', (
        ('widgets', ('counter', 'lower')),
        ('widget_ms', ('timing', 'lower', 'ms')),
        ('note', ('info',)),
    ))
    _SCHEMA.setdefault('perflab._sleep', (('widgets', ('counter',
                                                       'lower')),))

    def _scenario_quick(best_of):
        return ({'widgets': 1, 'widget_ms': 1.0, 'note': 'test'},
                {'widget_ms': [1.0, 1.1]}, {'kind': 'test'})

    def _scenario_sleep(best_of):
        _harness.stage('sleeping')
        time.sleep(3600)
        return ({'widgets': 0}, {}, {})

    SCENARIOS['_quick'] = _scenario_quick
    SCENARIOS['_sleep'] = _scenario_sleep


# ------------------------------------------------------------- plumbing
def _resolve_backend(allow_cpu):
    """Decide the backend for a round, bench.py-style: a deliberate
    JAX_PLATFORMS=cpu run is CPU with NO fallback reason; otherwise the
    subprocess probe must reach a TPU, and anything else is either a
    recorded fallback (allow_cpu) or a structured hard failure.
    Returns (platform, fallback_reason, extra_child_env) or exits."""
    if 'cpu' in (os.environ.get('JAX_PLATFORMS') or ''):
        return 'cpu', None, {}
    platform, kind_or_reason = _harness.probe_backend()
    if platform == 'tpu':
        print('PERFLAB: backend ok: tpu (%s)' % kind_or_reason,
              file=sys.stderr)
        return 'tpu', None, {}
    reason = kind_or_reason if platform is None else \
        "probe reached backend '%s', not tpu" % platform
    if not allow_cpu:
        print('PERFLAB: backend is not TPU — %s' % reason, file=sys.stderr)
        print('PERFLAB: set --allow-cpu (or PERFLAB_ALLOW_CPU=1) to '
              'record CPU numbers anyway', file=sys.stderr)
        _harness.emit_error('cpu_fallback', reason)
        sys.exit(3)
    print('PERFLAB: falling back to CPU — %s' % reason, file=sys.stderr)
    return 'cpu', reason if platform is None else None, \
        {'JAX_PLATFORMS': 'cpu'}


def _run_child(name, budget, best_of, fallback, extra_env, platform,
               cache_root=None):
    """One subprocess-isolated scenario.  Returns a ledger record —
    success, or a structured {"error": "timeout"|...} record."""
    from paddle_tpu.observability import perflab as pl

    env = dict(os.environ)
    env.update(extra_env)
    env.setdefault('PT_KERNELGEN', '1')
    if cache_root is not None:
        # every scenario lowers against its OWN fresh compile cache, so
        # compile/codegen counters (kernelgen_ops, compiles, ...) are
        # reproducible by construction — independent of whatever an
        # ambient PT_CACHE_DIR (e.g. ci_smoke's shared cache, warmed by
        # earlier gates) happens to contain
        env['PT_CACHE_DIR'] = os.path.join(cache_root, name)
    if fallback:
        env['PERFLAB_FALLBACK'] = fallback
    if name == 'pod_parallel' and platform == 'cpu':
        # give the allreduce microbench a 2-device mesh to measure
        flags = env.get('XLA_FLAGS', '')
        if 'xla_force_host_platform_device_count' not in flags:
            env['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=2').strip()
    cmd = [sys.executable, os.path.abspath(__file__), 'child',
           '--scenario', name, '--best-of', str(best_of)]
    t0 = time.time()
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        out, err = proc.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        stage = 'unknown'
        for line in reversed((err or '').splitlines()):
            if ': stage=' in line:
                stage = line.split(': stage=', 1)[1].strip()
                break
        print('PERFLAB: scenario %s TIMED OUT after %.0fs (stage=%s)'
              % (name, budget, stage), file=sys.stderr)
        return pl.error_record(name, 'timeout', stage=stage,
                               detail='child exceeded %.0fs budget'
                                      % budget)
    dt = time.time() - t0
    rec = None
    for line in reversed((out or '').strip().splitlines()):
        if line.startswith('{'):
            try:
                rec = json.loads(line)
            except ValueError:
                pass
            break
    if rec is None:
        tail = (err or out or '').strip().splitlines()[-6:]
        return pl.error_record(name, 'crash',
                               detail='rc=%r: %s' % (proc.returncode,
                                                     ' | '.join(tail)))
    if 'schema' not in rec and 'error' in rec:
        # the _harness JSON tail from a crashed child — promote it to a
        # ledger failure record, keeping its stage attribution
        return pl.error_record(name, rec['error'], stage=rec.get('stage'),
                               detail=rec.get('detail'))
    try:
        pl.validate_record(rec)
    except ValueError as e:
        return pl.error_record(name, 'schema_violation', detail=e)
    if 'error' not in rec:
        print('PERFLAB: scenario %s ok in %.1fs' % (name, dt),
              file=sys.stderr)
    return rec


def cmd_run(args):
    from paddle_tpu.observability import perflab as pl

    names = ([s.strip() for s in args.scenarios.split(',') if s.strip()]
             if args.scenarios else list(MATRIX))
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        sys.exit('perflab: unknown scenario(s) %s (known: %s)'
                 % (unknown, ', '.join(sorted(SCENARIOS))))
    allow_cpu = args.allow_cpu or \
        os.environ.get('PERFLAB_ALLOW_CPU',
                       os.environ.get('BENCH_ALLOW_CPU', '0')) in ('1',
                                                                   'true')
    _harness.stage('probe')
    platform, fallback, extra_env = _resolve_backend(allow_cpu)
    ledger = args.ledger
    # children compile against a fresh per-scenario cache so the
    # deterministic counters in the record never depend on ambient cache
    # state; PERFLAB_CACHE_DIR pins a persistent root instead (explicit
    # warm-cache mode, e.g. to amortise TPU compiles across rounds)
    pinned_cache = os.environ.get('PERFLAB_CACHE_DIR')
    cache_root = pinned_cache or tempfile.mkdtemp(prefix='perflab_cache_')
    records, failed = [], []
    try:
        for name in names:
            _harness.stage(name)
            rec = _run_child(name, args.budget_s, args.best_of, fallback,
                             extra_env, platform, cache_root=cache_root)
            pl.append_record(ledger, rec)
            records.append(rec)
            if 'error' in rec:
                failed.append(name)
    finally:
        if not pinned_cache:
            shutil.rmtree(cache_root, ignore_errors=True)
    summary = {
        'scenarios': len(records),
        'ok': len(records) - len(failed),
        'failed': failed,
        'platform': platform,
        'fallback': fallback,
        'ledger': ledger,
    }
    print(json.dumps(summary))
    return 1 if failed else 0


def cmd_child(args):
    from paddle_tpu.observability import perflab as pl

    name = args.scenario
    if name not in SCENARIOS:
        sys.exit('perflab child: unknown scenario %r' % name)
    fallback = os.environ.get('PERFLAB_FALLBACK') or None
    metrics, spread, config = SCENARIOS[name](args.best_of)
    _harness.stage('report')
    rec = pl.build_record(name, metrics, spread=spread, config=config,
                          fallback=fallback)
    print(json.dumps(rec))
    return 0


def cmd_podworker(args):
    """Internal: one lockstep trainer for the pod_parallel scenario —
    the fault_soak tiny model, steps/s over a fixed step count."""
    import numpy as np
    import paddle_tpu as fluid
    import fault_soak

    main_prog, startup, loss = fault_soak.build_model(fluid)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = fault_soak.feed_at(0)
        for _ in range(2):
            out = exe.run(main_prog, feed=feed, fetch_list=[loss])
        np.asarray(out[0])
        t0 = time.perf_counter()
        for i in range(args.steps):
            out = exe.run(main_prog, feed=fault_soak.feed_at(i),
                          fetch_list=[loss], return_numpy=False)
        np.asarray(out[0])
        dt = time.perf_counter() - t0
    print(json.dumps({'steps_per_s': args.steps / dt}))
    return 0


def cmd_compare(args):
    from paddle_tpu.observability import perflab as pl

    if not os.path.exists(args.baseline):
        sys.exit('perflab compare: no baseline at %s (run `perflab '
                 'bless` to create one)' % args.baseline)
    with open(args.baseline) as f:
        doc = json.load(f)
    records = pl.read_ledger(args.ledger)
    names = ([s.strip() for s in args.scenarios.split(',') if s.strip()]
             if args.scenarios else None)
    fail_on = None if args.fail_on == 'none' else args.fail_on
    rc, reports = pl.compare_ledger(doc, records, fail_on=fail_on,
                                    scenarios=names)
    for rep in reports:
        print(json.dumps(rep))
    summary = {
        'compare': {s: sum(1 for r in reports if r['status'] == s)
                    for s in ('ok', 'regression', 'refused', 'missing')},
        'baseline_git_sha': doc.get('blessed_git_sha'),
        'rc': rc,
    }
    print(json.dumps(summary))
    if rc == 2:
        print('PERFLAB: comparison REFUSED — see reasons above '
              '(a cpu-fallback or mismatched-backend record cannot '
              'gate against this baseline)', file=sys.stderr)
    elif rc:
        print('PERFLAB: regression(s) detected', file=sys.stderr)
    return rc


def cmd_check(args):
    """The ci assertion: every requested scenario has a newest ledger
    record that is schema-valid, non-error, and provenance-complete."""
    from paddle_tpu.observability import perflab as pl

    names = ([s.strip() for s in args.scenarios.split(',') if s.strip()]
             if args.scenarios else list(MATRIX))
    latest = pl.latest_per_scenario(pl.read_ledger(args.ledger))
    bad = []
    for name in names:
        rec = latest.get(name)
        if rec is None:
            bad.append('%s: no ledger record' % name)
            continue
        if 'error' in rec:
            bad.append('%s: failure record (%s, stage=%s)'
                       % (name, rec.get('error'), rec.get('stage')))
            continue
        try:
            pl.validate_record(rec)
        except ValueError as e:
            bad.append(str(e))
    print(json.dumps({'checked': names, 'failures': bad}))
    if bad:
        for b in bad:
            print('PERFLAB: check FAILED: %s' % b, file=sys.stderr)
        return 1
    return 0


def cmd_bless(args):
    from paddle_tpu.observability import perflab as pl

    records = pl.read_ledger(args.ledger)
    names = ([s.strip() for s in args.scenarios.split(',') if s.strip()]
             if args.scenarios else None)
    if names:
        records = [r for r in records if r['scenario'] in names]
    doc = pl.bless(records,
                   default_timing_tolerance=args.timing_tolerance)
    with open(args.out, 'w') as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write('\n')
    print(json.dumps({'blessed': sorted(doc['scenarios']),
                      'out': args.out,
                      'git_sha': doc['blessed_git_sha']}))
    return 0


def cmd_list(args):
    from paddle_tpu.observability import perflab as pl

    for name in sorted(SCENARIOS):
        specs = pl.metric_specs(name)
        counters = [k for k, s in specs.items() if s[0] == 'counter']
        timings = [k for k, s in specs.items() if s[0] == 'timing']
        print(json.dumps({'scenario': name, 'counters': sorted(counters),
                          'timings': sorted(timings),
                          'in_matrix': name in MATRIX}))
    return 0


def main():
    ap = argparse.ArgumentParser(prog='perflab', description=__doc__)
    sub = ap.add_subparsers(dest='cmd', required=True)

    p = sub.add_parser('run', help='run the scenario matrix')
    p.add_argument('--scenarios', default=None,
                   help='comma list (default: the full matrix)')
    p.add_argument('--ledger', default=os.environ.get('PT_PERF_LEDGER',
                                                      DEFAULT_LEDGER))
    p.add_argument('--budget-s', type=float,
                   default=float(os.environ.get('PERFLAB_BUDGET_S',
                                                '600')),
                   help='per-scenario child budget; a child past it is '
                        'killed and gets a structured timeout record')
    p.add_argument('--best-of', type=int,
                   default=int(os.environ.get('PERFLAB_BEST_OF', '3')),
                   help='timing trials per scenario (spread is recorded)')
    p.add_argument('--allow-cpu', action='store_true',
                   help='record CPU numbers when no TPU is reachable '
                        '(provenance carries the fallback reason)')
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser('child', help='internal: run ONE scenario '
                                     'in-process and print its record')
    p.add_argument('--scenario', required=True)
    p.add_argument('--best-of', type=int, default=3)
    p.set_defaults(fn=cmd_child)

    p = sub.add_parser('podworker', help='internal: pod_parallel worker')
    p.add_argument('--steps', type=int, default=8)
    p.set_defaults(fn=cmd_podworker)

    p = sub.add_parser('compare', help='diff newest records vs baseline')
    p.add_argument('--baseline', default=DEFAULT_BASELINE)
    p.add_argument('--ledger', default=os.environ.get('PT_PERF_LEDGER',
                                                      DEFAULT_LEDGER))
    p.add_argument('--scenarios', default=None)
    p.add_argument('--fail-on', default='none',
                   choices=('regression', 'none'),
                   help='regression: exit 1 on any counter/timing '
                        'regression or missing scenario, exit 2 on a '
                        'structured refusal')
    p.set_defaults(fn=cmd_compare)

    p = sub.add_parser('check', help='assert schema-valid provenanced '
                                     'records exist per scenario')
    p.add_argument('--ledger', default=os.environ.get('PT_PERF_LEDGER',
                                                      DEFAULT_LEDGER))
    p.add_argument('--scenarios', default=None)
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser('bless', help='write newest records as baseline')
    p.add_argument('--ledger', default=os.environ.get('PT_PERF_LEDGER',
                                                      DEFAULT_LEDGER))
    p.add_argument('--out', default=DEFAULT_BASELINE)
    p.add_argument('--scenarios', default=None)
    p.add_argument('--timing-tolerance', type=float, default=0.5)
    p.set_defaults(fn=cmd_bless)

    p = sub.add_parser('list', help='print the scenario registry')
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser('probe', help='one-shot diagnostic harnesses '
                                     '(absorbed tools/measure.py)')
    p.add_argument('rest', nargs=argparse.REMAINDER)
    p.set_defaults(fn=None)

    p = sub.add_parser('models', help='reference model-matrix benchmark '
                                      '(absorbed tools/fluid_benchmark.py)')
    p.add_argument('rest', nargs=argparse.REMAINDER)
    p.set_defaults(fn=None)

    args = ap.parse_args()
    if args.cmd == 'probe':
        import _probes
        return _probes.probe_main(args.rest)
    if args.cmd == 'models':
        import _probes
        return _probes.models_main(args.rest)
    return args.fn(args)


if __name__ == '__main__':
    _harness.set_tool('PERFLAB')
    scenario = None
    if 'child' in sys.argv[1:2] and '--scenario' in sys.argv:
        scenario = sys.argv[sys.argv.index('--scenario') + 1]
    extra = {'scenario': scenario} if scenario else {}
    _harness.main_guard(main, watchdog_env='PERFLAB_WATCHDOG_S',
                        flight_tag='perflab.watchdog', **extra)
