#!/usr/bin/env python
"""lint_lite — stdlib-only fallback for the ci_smoke ruff gate.

The CI container cannot pip-install ruff, so this covers the highest-
signal, zero-false-positive slice of `ruff check` with nothing but ast:

  * E999  syntax error (the file does not parse)
  * F401  imported name never used anywhere in the module

Deliberately conservative — an import is only reported when its bound
name appears in NO identifier and NO string literal of the module (string
scanning keeps __all__ re-exports, doctest snippets, and lazy
`globals()[name]` idioms quiet), the line carries no `# noqa`, and the
file is not an `__init__.py` (re-export surface by design).

    python tools/lint_lite.py paddle_tpu/ tests/ tools/

Exit 1 when findings exist, 0 otherwise.
"""
import ast
import os
import re
import sys

__all__ = ['check_file', 'main']

_WORD = re.compile(r'[A-Za-z_][A-Za-z0-9_]*')


def _collect_imports(tree):
    """[(bound_name, lineno)] for plain imports; star imports skipped."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split('.')[0]
                out.append((name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.module == '__future__':
                continue
            for a in node.names:
                if a.name == '*':
                    continue
                out.append((a.asname or a.name, node.lineno))
    return out


def _used_words(tree):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            used.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.update(_WORD.findall(node.value))
    return used


def check_file(path):
    with open(path, 'rb') as f:
        src = f.read()
    try:
        text = src.decode('utf-8')
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return ['%s:%s: E999 syntax error: %s' % (path, e.lineno, e.msg)]
    except UnicodeDecodeError as e:
        return ['%s:1: E999 not utf-8: %s' % (path, e)]
    if os.path.basename(path) == '__init__.py':
        return []
    lines = text.split('\n')
    findings = []
    imports = _collect_imports(tree)
    if not imports:
        return findings
    used = _used_words(tree)
    counts = {}
    for name, _ in imports:
        counts[name] = counts.get(name, 0) + 1
    for name, lineno in imports:
        if name in used or name.startswith('_'):
            continue
        if counts[name] > 1:
            # re-imported under a guard (try/except fallbacks): the ast
            # walk cannot tell which binding wins — stay quiet
            continue
        line = lines[lineno - 1] if lineno - 1 < len(lines) else ''
        if 'noqa' in line:
            continue
        findings.append("%s:%d: F401 '%s' imported but unused"
                        % (path, lineno, name))
    return findings


def _iter_py(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d not in ('__pycache__', '.git')]
            for f in sorted(files):
                if f.endswith('.py'):
                    yield os.path.join(root, f)


def main(argv=None):
    paths = (argv if argv is not None else sys.argv[1:]) or ['.']
    findings = []
    n = 0
    for path in _iter_py(paths):
        n += 1
        findings.extend(check_file(path))
    for f in findings:
        print(f)
    print('lint_lite: %d file(s), %d finding(s)' % (n, len(findings)))
    return 1 if findings else 0


if __name__ == '__main__':
    sys.exit(main())
