#!/usr/bin/env bash
# CI smoke: the tier-1 test command from ROADMAP.md, then a CPU bench.py
# run whose JSON line is validated against the expected schema — bench
# drift (a renamed or dropped key) fails fast instead of silently.
set -u
cd "$(dirname "$0")/.."

echo "== ci_smoke: pt-lint over bundled models =="
# static-analysis gate (docs/analysis.md): every bundled model program
# must lint clean of error-severity findings (shape/dtype coverage of
# every op type included — an unknown op is a warning, a shape error is
# an error, and either class regressing shows up here)
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/pt_lint.py \
    --all-builtin --fail-on error
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "ci_smoke: pt-lint FAILED (rc=$lint_rc)"
fi

echo "== ci_smoke: pt-lint over bundled models (post-optimization) =="
# the PT_OPT rewriter gate, part 1 (docs/passes.md): every zoo program
# must ALSO lint error-free after the optimizing pipeline rewrote it —
# a pass emitting broken fused/folded ops shows up here
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/pt_lint.py \
    --all-builtin --optimize --fail-on error
opt_lint_rc=$?
if [ "$opt_lint_rc" -ne 0 ]; then
    echo "ci_smoke: pt-lint --optimize FAILED (rc=$opt_lint_rc)"
fi

echo "== ci_smoke: opt pipeline op-count + bitwise parity =="
# the PT_OPT rewriter gate, part 2: the bench transformer program must
# shrink through the pipeline, and PT_OPT=1 training must be bitwise
# equal to PT_OPT=0 (losses AND end-of-run param/Adam state).
# PT_KERNELGEN=0 pins the kernel tier OFF so this gate isolates the
# rewriter itself (the strict-kernelgen and autotune gates below own
# the generated-kernel parity story)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 PT_KERNELGEN=0 \
    python - <<'EOF'
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import passes
from paddle_tpu.models import transformer as tr

def build(B=2, T=16):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=256, trg_vocab=256, max_len=T,
                           n_layer=2, n_head=2, d_model=32, d_inner=64,
                           dropout=0.1, use_flash=False)
    return main, startup, out

main, _, out = build()
opt, stats = passes.optimize_program(main, (out['loss'].name,))
raw, cut = stats['op_count_raw'], stats['op_count_opt']
if not cut < raw:
    sys.exit('ci_smoke: opt pipeline did not shrink the program '
             '(raw=%d opt=%d)' % (raw, cut))
print('ci_smoke: opt op-count %d -> %d (-%.0f%%, %d fused, %d removed)'
      % (raw, cut, 100.0 * (raw - cut) / raw, stats['ops_fused'],
         stats['ops_removed']))

def train(pt_opt):
    os.environ['PT_OPT'] = pt_opt
    main, startup, out = build()
    main.set_amp(True)
    exe, scope = fluid.Executor(), fluid.Scope()
    rng = np.random.RandomState(0)
    feed = tr.synthetic_batch(rng, 2, 16, 256)
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[out['loss']])[0])
                  for _ in range(2)]
    return losses, {n: np.asarray(v) for n, v in scope.vars.items()}

l1, s1 = train('1')
l0, s0 = train('0')
for a, b in zip(l1, l0):
    if not np.array_equal(a, b):
        sys.exit('ci_smoke: PT_OPT=1 losses diverge from PT_OPT=0: '
                 '%r vs %r' % (a, b))
bad = [n for n in s1 if not np.array_equal(s1[n], s0.get(n))]
if set(s1) != set(s0) or bad:
    sys.exit('ci_smoke: PT_OPT=1 end-of-run state diverges: %s'
             % bad[:5])
print('ci_smoke: PT_OPT=1 bitwise-equal to PT_OPT=0 '
      '(%d steps, %d state arrays)' % (len(l1), len(s1)))
EOF
opt_gate_rc=$?
if [ "$opt_gate_rc" -ne 0 ]; then
    echo "ci_smoke: opt pipeline gate FAILED (rc=$opt_gate_rc)"
fi

echo "== ci_smoke: shard pass — 2-device mesh bitwise parity =="
# the GSPMD-style partitioner gate (docs/passes.md, shard pass): the
# bench transformer on a 2-device CPU mesh with PT_SHARD=1 must (a)
# train bitwise-equal to the SAME optimized program on a single device
# (replicated feeds; ZeRO-sharded params + Adam state on the mesh side),
# (b) lint clean of D017 after the rewrite, and (c) insert a stable set
# of collectives — two optimize runs, identical reshards_inserted and
# collective_bytes
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 PT_KERNELGEN=0 \
    XLA_FLAGS=--xla_force_host_platform_device_count=2 \
    python - <<'EOF'
import sys

import numpy as np
import jax

import paddle_tpu as fluid
from paddle_tpu.core import passes
from paddle_tpu.analysis import lint_program
from paddle_tpu.models import transformer as tr
from paddle_tpu.parallel.mesh import make_mesh

def build(B=2, T=16):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=256, trg_vocab=256, max_len=T,
                           n_layer=2, n_head=2, d_model=32, d_inner=64,
                           dropout=0.1, use_flash=False)
    main.set_amp(True)
    main.set_mesh_axes({'data': 2})
    # replicated feeds: the sharded run sees the SAME global batch as
    # the single-device run, so parity is bitwise, not allclose
    for v in main.global_block().vars.values():
        if getattr(v, 'is_data', False) and v.shape is not None:
            v.sharding = (None,) * len(v.shape)
    return main, startup, out

main, _, out = build()
fetch = (out['loss'].name,)
opt1, stats1 = passes.optimize_program(main, fetch)
opt2, stats2 = passes.optimize_program(main, fetch)
s1, s2 = stats1['passes']['shard'], stats2['passes']['shard']
for k in ('reshards_inserted', 'collective_bytes', 'grad_allreduce',
          'all_gathers'):
    if s1[k] != s2[k]:
        sys.exit('ci_smoke: shard pass unstable across runs: %s %r vs %r'
                 % (k, s1[k], s2[k]))
if not (s1['grad_allreduce'] or s1['all_gathers']
        or s1['reshards_inserted']):
    sys.exit('ci_smoke: shard pass inserted no collectives on a meshed '
             'transformer — the partitioner is not running')
res = lint_program(opt1, fetch_names=fetch)
d17 = [d for d in res.diagnostics if d.code == 'D017']
if d17:
    sys.exit('ci_smoke: D017 on the shard-optimized transformer: %s'
             % [d.message[:90] for d in d17[:3]])
print('ci_smoke: shard pass stable (%d grad_allreduce, %d all_gather, '
      '%d reshard, %d bytes), zero D017'
      % (s1['grad_allreduce'], s1['all_gathers'], s1['reshards_inserted'],
         s1['collective_bytes']))

def train(mesh):
    main, startup, out = build()
    exe = fluid.Executor(mesh=make_mesh(data=2) if mesh else None)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = tr.synthetic_batch(rng, 2, 16, 256)
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[out['loss']])[0])
                  for _ in range(2)]
        state = {n: np.asarray(v) for n, v in scope.vars.items()}
    return losses, state

lm, sm = train(True)
ls, ss = train(False)
for a, b in zip(lm, ls):
    if not np.array_equal(a, b):
        sys.exit('ci_smoke: sharded losses diverge from single-device: '
                 '%r vs %r' % (a, b))
# state keys differ only by the fresh unique-name counter per build;
# compare sorted positionally (same build => same order)
if len(sm) != len(ss):
    sys.exit('ci_smoke: sharded run state count %d != single-device %d'
             % (len(sm), len(ss)))
bad = [n1 for (n1, a), (n2, b)
       in zip(sorted(sm.items()), sorted(ss.items()))
       if not np.array_equal(a, b)]
if bad:
    sys.exit('ci_smoke: sharded end-of-run state diverges: %s' % bad[:5])
print('ci_smoke: PT_SHARD=1 2-device mesh bitwise-equal to '
      'single-device (%d steps, %d state arrays)' % (len(lm), len(sm)))
EOF
shard_rc=$?
if [ "$shard_rc" -ne 0 ]; then
    echo "ci_smoke: shard pass gate FAILED (rc=$shard_rc)"
fi

echo "== ci_smoke: strict-emit zoo coverage =="
# direct-emitter gate, part 1 (docs/emitter.md): every zoo program must
# be fully emit-capable — zero D015 lint findings, an EmitEngine builds
# without fallback under PT_STRICT_EMIT=1, and (dense-feed models) the
# whole training program jit-TRACES through the emitter with synthesized
# params/feeds — runtime emission exercised, no backend compile paid.
# One op losing its emit rule or a new builtin op landing without one
# fails here, not as a silent cold-start regression.
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_EMIT=1 PT_STRICT_EMIT=1 \
    PT_CACHE=0 python - <<'EOF'
import os
import sys
import time

import numpy as np

sys.path.insert(0, 'tools')
import pt_lint  # noqa: E402

from paddle_tpu.core import emit, passes  # noqa: E402
from paddle_tpu.core import executor as ptex  # noqa: E402

fails = []
for name in pt_lint.builtin_names():
    prog, feeds, fetches = pt_lint._zoo_entry(name)()
    res = prog.lint(feed_names=feeds, fetch_list=fetches)
    d15 = [d for d in res if d.code == 'D015']
    if d15:
        fails.append((name, d15[0].render()))
        continue
    opt_prog, _ = passes.maybe_optimize(prog, tuple(fetches))
    try:
        engine = emit.build_engine(opt_prog, feeds, fetches)
    except emit.EmitFallback as e:
        fails.append((name, 'EmitFallback: %s' % e))
        continue
    block = prog.global_block()
    if any(getattr(block.vars[f], 'lod_level', 0) for f in feeds):
        print('ci_smoke: %-14s emit-capable (%d op sigs; LoD feeds -> '
              'static coverage only)' % (name, len(engine.coverage)))
        continue
    rng = np.random.RandomState(0)
    feed_vals = {}
    for f in feeds:
        v = block.vars[f]
        shape = tuple(2 if d in (-1, None) else int(d) for d in v.shape)
        dt = np.dtype(v.dtype)
        feed_vals[f] = (np.zeros(shape, dt) if dt.kind in 'iub'
                        else rng.standard_normal(shape).astype(dt))
    jit_fn, params_in, _ = ptex._lower(
        opt_prog, feeds, fetches, donate=False, check_nan=False,
        emit_engine=engine)
    params = {}
    for pn in params_in:
        v = block.vars[pn]
        params[pn] = np.zeros(tuple(int(d) for d in v.shape),
                              np.dtype(v.dtype))
    t0 = time.perf_counter()
    try:
        jit_fn.trace(params, feed_vals, np.uint32(0))
    except (emit.EmitError, emit.EmitFallback) as e:
        fails.append((name, 'trace-time: %s' % e))
        continue
    print('ci_smoke: %-14s traced under strict emit (%d op sigs, %.1fs)'
          % (name, len(engine.coverage), time.perf_counter() - t0))
if fails:
    for name, why in fails:
        print('ci_smoke: STRICT-EMIT GAP in %s: %s' % (name, why))
    sys.exit('ci_smoke: %d zoo program(s) not fully emit-capable'
             % len(fails))
print('ci_smoke: all %d zoo programs emit with zero fallbacks '
      'under PT_STRICT_EMIT=1' % len(pt_lint.builtin_names()))
EOF
emit_zoo_rc=$?
if [ "$emit_zoo_rc" -ne 0 ]; then
    echo "ci_smoke: strict-emit zoo gate FAILED (rc=$emit_zoo_rc)"
fi

echo "== ci_smoke: strict-kernelgen coverage =="
# Pallas codegen gate (docs/kernels.md): the bench transformer and a
# fused-Adam program must train end-to-end under PT_KERNELGEN=1
# PT_STRICT_KERNELS=1 — every fused_elementwise group lowers through a
# generated kernel, zero fallbacks (a sub-op losing its KERNEL_RULES
# entry raises here, naming the sub-op, instead of silently un-fusing
# the optimizer step).  The optimized programs must also carry zero
# D016 lint findings — the static face of the same contract.
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_KERNELGEN=1 \
    PT_STRICT_KERNELS=1 PT_CACHE=0 python - <<'EOF'
import sys

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core import passes
from paddle_tpu.models import transformer as tr


def check_d016(main, fetch_names, label):
    opt, _ = passes.optimize_program(main, tuple(fetch_names))
    res = opt.lint(fetch_list=list(fetch_names))
    d16 = [d for d in res if d.code == 'D016']
    if d16:
        sys.exit('ci_smoke: KERNELGEN GAP in %s: %s'
                 % (label, d16[0].render()))


def counters():
    c = obs.counters()
    return (c.get('kernelgen.ops') or 0,
            c.get('kernelgen.fallbacks') or 0,
            c.get('kernel.fallbacks') or 0)


# 1. bench transformer (smoke shapes), AMP + dropout, 2 steps
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        out = tr.build(src_vocab=256, trg_vocab=256, max_len=16,
                       n_layer=2, n_head=2, d_model=32, d_inner=64,
                       dropout=0.1, use_flash=False)
main.set_amp(True)
check_d016(main, (out['loss'].name,), 'bench transformer')
exe, scope = fluid.Executor(), fluid.Scope()
rng = np.random.RandomState(0)
feed = tr.synthetic_batch(rng, 2, 16, 256)
with fluid.scope_guard(scope):
    exe.run(startup)
    for _ in range(2):
        loss, = exe.run(main, feed=feed, fetch_list=[out['loss']])
        if not np.isfinite(np.asarray(loss)).all():
            sys.exit('ci_smoke: non-finite loss under PT_KERNELGEN=1')
ops, kg_fb, k_fb = counters()
if ops < 1:
    sys.exit('ci_smoke: kernelgen.ops=%r — PT_KERNELGEN=1 but no fused '
             'group lowered through a generated kernel' % ops)
print('ci_smoke: transformer trained strict-kernelgen '
      '(%d groups via generated kernels)' % ops)

# 2. fused-Adam program: the whole optimizer step must survive strict
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        x = fluid.layers.data('x', shape=[64], dtype='float32')
        h = fluid.layers.fc(x, 64, act='relu')
        y = fluid.layers.fc(h, 64)
        loss = fluid.layers.reduce_mean(y * y)
        fluid.optimizer.Adam(1e-3).minimize(loss)
check_d016(main, (loss.name,), 'fused-Adam program')
exe, scope = fluid.Executor(), fluid.Scope()
feed = {'x': np.random.RandomState(1).randn(8, 64).astype('float32')}
with fluid.scope_guard(scope):
    exe.run(startup)
    for _ in range(2):
        exe.run(main, feed=feed, fetch_list=[loss])
ops2, kg_fb, k_fb = counters()
if ops2 <= ops:
    sys.exit('ci_smoke: fused-Adam program lowered no generated kernels '
             '(kernelgen.ops %r -> %r)' % (ops, ops2))
if kg_fb or k_fb:
    sys.exit('ci_smoke: %d kernelgen / %d kernel fallback(s) under '
             'PT_STRICT_KERNELS=1 — fallback accounting is broken'
             % (kg_fb, k_fb))
print('ci_smoke: fused-Adam trained strict-kernelgen '
      '(%d groups total, zero fallbacks)' % ops2)
EOF
kg_zoo_rc=$?
if [ "$kg_zoo_rc" -ne 0 ]; then
    echo "ci_smoke: strict-kernelgen gate FAILED (rc=$kg_zoo_rc)"
fi

echo "== ci_smoke: autotune persistence (search once, reuse forever) =="
# tile/block autotuner gate (docs/kernels.md): two FRESH processes share
# one PT_CACHE_DIR.  Run 1 (cold) must pay timed block-size searches
# (kernelgen.autotune_searches > 0) and persist every choice under
# <cache>/autotune/.  Between runs the compiled-executable entries are
# deleted — but NOT the autotune store — so run 2 rebuilds every kernel
# plan yet must answer every block-size lookup from disk:
# autotune_searches == 0, autotune_cache_hits > 0, and still zero
# fallbacks under PT_STRICT_KERNELS=1.
autotune_cache=$(mktemp -d /tmp/pt_autotune_cache.XXXXXX)
autotune_gate() {
    timeout -k 10 600 env JAX_PLATFORMS=cpu PT_KERNELGEN=1 \
        PT_STRICT_KERNELS=1 PT_AUTOTUNE=1 PT_CACHE=1 \
        PT_CACHE_DIR="$autotune_cache" AUTOTUNE_PHASE="$1" python - <<'EOF'
import os
import sys

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.models import transformer as tr

phase = os.environ['AUTOTUNE_PHASE']
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        out = tr.build(src_vocab=256, trg_vocab=256, max_len=16,
                       n_layer=2, n_head=2, d_model=32, d_inner=64,
                       dropout=0.1, use_flash=False)
main.set_amp(True)
exe, scope = fluid.Executor(), fluid.Scope()
feed = tr.synthetic_batch(np.random.RandomState(0), 2, 16, 256)
with fluid.scope_guard(scope):
    exe.run(startup)
    for _ in range(2):
        exe.run(main, feed=feed, fetch_list=[out['loss']])
c = obs.counters()
searches = c.get('kernelgen.autotune_searches') or 0
hits = c.get('kernelgen.autotune_cache_hits') or 0
fallbacks = ((c.get('kernelgen.fallbacks') or 0) +
             (c.get('kernel.fallbacks') or 0))
print('ci_smoke: autotune %s run: searches=%d cache_hits=%d fallbacks=%d'
      % (phase, searches, hits, fallbacks))
if fallbacks:
    sys.exit('ci_smoke: %d fallback(s) under PT_STRICT_KERNELS=1 with '
             'the autotuner on' % fallbacks)
if phase == 'cold':
    if searches < 1:
        sys.exit('ci_smoke: cold run paid no autotune searches — '
                 'PT_AUTOTUNE=1 but the autotuner never engaged')
else:
    if searches != 0:
        sys.exit('ci_smoke: warm run re-searched %d signature(s) — the '
                 'persisted autotune choices were not honored' % searches)
    if hits < 1:
        sys.exit('ci_smoke: warm run answered no block-size lookups from '
                 'the persisted autotune store')
EOF
}
autotune_gate cold
autotune_cold_rc=$?
if [ "$autotune_cold_rc" -eq 0 ]; then
    # drop compiled executables but KEEP the autotune store: run 2 must
    # rebuild every kernel plan and answer every block choice from disk
    find "$autotune_cache" -mindepth 1 -maxdepth 1 ! -name autotune \
        -exec rm -rf {} +
    autotune_gate warm
    autotune_warm_rc=$?
else
    autotune_warm_rc=1
fi
autotune_rc=$(( autotune_cold_rc || autotune_warm_rc ))
if [ "$autotune_rc" -ne 0 ]; then
    echo "ci_smoke: autotune persistence gate FAILED"
fi
rm -rf "$autotune_cache"

echo "== ci_smoke: ruff =="
# style/bug gate with the committed ruff.toml; the container image may
# not ship ruff (and pip installs are off-limits in CI images) — fall
# back through `python -m ruff` to the stdlib-AST checker
# tools/lint_lite.py so SOME source lint always gates the smoke
if command -v ruff >/dev/null 2>&1; then
    ruff check paddle_tpu/ tests/ tools/
    ruff_rc=$?
elif python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check paddle_tpu/ tests/ tools/
    ruff_rc=$?
else
    echo "ci_smoke: ruff not installed; running tools/lint_lite.py"
    python tools/lint_lite.py paddle_tpu/ tests/ tools/
    ruff_rc=$?
fi

echo "== ci_smoke: pt-lint --json schema =="
# the machine-readable lint surface is a contract like the bench
# telemetry schema: validate every --all-builtin --json --memplan
# result against the key tuples diagnostics.py pins, and require the
# serving-side generation entries to be present and error-free
timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import subprocess
import sys

from paddle_tpu.analysis.diagnostics import (CODES, SEVERITIES,
                                             DIAG_JSON_KEYS,
                                             RESULT_JSON_KEYS)
from paddle_tpu.analysis.passes.memplan import MEMPLAN_JSON_KEYS

proc = subprocess.run(
    [sys.executable, 'tools/pt_lint.py', '--all-builtin', '--json',
     '--memplan', '--fail-on', 'error'],
    capture_output=True, text=True)
if proc.returncode not in (0, 2):
    sys.exit('ci_smoke: pt_lint --json crashed (rc=%d):\n%s'
             % (proc.returncode, proc.stderr[-2000:]))
out = json.loads(proc.stdout)
if set(out) != {'fail_on', 'results'}:
    sys.exit('ci_smoke: unexpected top-level keys %s' % sorted(out))
results = out['results']
for label in ('builtin:llama_prefill', 'builtin:llama_decode'):
    if label not in results:
        sys.exit('ci_smoke: generation program %s missing from '
                 '--all-builtin' % label)
checked = 0
for label, res in results.items():
    if 'error' in res:
        sys.exit('ci_smoke: %s failed to build: %s'
                 % (label, res['error']))
    if set(res) - {'memplan'} != set(RESULT_JSON_KEYS):
        sys.exit('ci_smoke: %s result keys %s != %s'
                 % (label, sorted(res), sorted(RESULT_JSON_KEYS)))
    if set(res['memplan']) != set(MEMPLAN_JSON_KEYS):
        sys.exit('ci_smoke: %s memplan keys %s != %s'
                 % (label, sorted(res['memplan']),
                    sorted(MEMPLAN_JSON_KEYS)))
    if res['errors']:
        sys.exit('ci_smoke: %s has %d lint error(s)'
                 % (label, res['errors']))
    for d in res['diagnostics']:
        if set(d) != set(DIAG_JSON_KEYS):
            sys.exit('ci_smoke: %s diagnostic keys %s != %s'
                     % (label, sorted(d), sorted(DIAG_JSON_KEYS)))
        if d['code'] not in CODES or d['severity'] not in SEVERITIES:
            sys.exit('ci_smoke: %s bad code/severity %s/%s'
                     % (label, d['code'], d['severity']))
        checked += 1
print('ci_smoke: pt_lint --json schema OK (%d programs, %d diagnostics, '
      'all memplans shaped)' % (len(results), checked))
EOF
lint_schema_rc=$?
if [ "$lint_schema_rc" -ne 0 ]; then
    echo "ci_smoke: pt-lint json schema gate FAILED (rc=$lint_schema_rc)"
fi

echo "== ci_smoke: fault-injection soak =="
# resilience gate (docs/robustness.md): a short training run survives the
# armed PT_FAULT matrix — NaN burst (divergence rollback), torn checkpoint
# write, compile-cache read/write OSErrors (retry_with_backoff), prefetch
# stall — and proves it with counters: recovery.rollbacks > 0,
# faults.injected > 0, zero post-recovery retraces, zero steady-state
# pipeline stalls.  Phase 2 rehearses preemption: SIGTERM mid-run (the
# handler flushes a final checkpoint), then a fresh process must
# auto-resume from it and finish.
soak_dir=$(mktemp -d /tmp/pt_soak.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=1 \
    PT_CACHE_DIR="$soak_dir/cache" \
    PT_FAULT="nan_step:at=4,ckpt_write:at=2,cache_read:at=1,cache_write:at=1,prefetch_stall:at=1:s=0.05" \
    python tools/fault_soak.py --steps 12 --ckpt "$soak_dir/ckpt" \
    --assert-recovery
soak_rc=$?
if [ "$soak_rc" -ne 0 ]; then
    echo "ci_smoke: fault-injection soak FAILED (rc=$soak_rc)"
fi

echo "== ci_smoke: preemption (SIGTERM) + auto-resume =="
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 \
    PT_FAULT="sigterm:at=6" \
    python tools/fault_soak.py --steps 12 --ckpt "$soak_dir/ckpt2"
term_rc=$?
if [ "$term_rc" -eq 0 ]; then
    echo "ci_smoke: SIGTERM fault did not terminate the soak (rc=0)"
    resume_rc=1
else
    timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 \
        python tools/fault_soak.py --steps 12 --ckpt "$soak_dir/ckpt2" \
        --expect-resume
    resume_rc=$?
fi
if [ "$resume_rc" -ne 0 ]; then
    echo "ci_smoke: preemption auto-resume FAILED (rc=$resume_rc)"
fi
rm -rf "$soak_dir"

echo "== ci_smoke: async executor soak (deferred nan poll, PT_ASYNC=1) =="
# fully-async gate (docs/async.md): the SAME fault soak but with the
# executor in async mode — launches return FetchFuture handles, the fused
# all-finite verdict stays device-resident between polls (PT_NAN_POLL=4),
# and a mid-window nan_step fault must trip a DEFERRED poll, roll back to
# the last clean-verdict checkpoint, and finish with finite losses.
# --expect-async requires nan_poll.polls>=1 AND nan_poll.trips>=1;
# --assert-recovery keeps steady-state stalls pinned at ZERO — the whole
# point of the async executor.
async_dir=$(mktemp -d /tmp/pt_async.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 PT_ASYNC=1 \
    PT_NAN_POLL=4 PT_FAULT="nan_step:at=5" \
    python tools/fault_soak.py --steps 16 --ckpt "$async_dir/ckpt" \
    --assert-recovery --expect-async
async_rc=$?
if [ "$async_rc" -ne 0 ]; then
    echo "ci_smoke: async executor soak FAILED (rc=$async_rc)"
fi
rm -rf "$async_dir"

echo "== ci_smoke: NaN forensics (bisection + quarantine heal, sync) =="
# forensics gate (docs/robustness.md): a single poisoned batch row
# (nan_step:at=5:row=3) trips the verdict; the forensic pipeline must
# replay the condemned window, bisect to the EXACT (step, op, row),
# quarantine the sample, HEAL the window by replaying it with the row
# substituted, and finish with losses bitwise-identical to an
# in-process uninjected reference run over the same quarantine —
# --expect-forensics asserts every link of that chain.
forensic_dir=$(mktemp -d /tmp/pt_forensic.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 \
    PT_FAULT="nan_step:at=5:row=3" \
    python tools/fault_soak.py --steps 12 --ckpt "$forensic_dir/ckpt" \
    --expect-forensics --assert-recovery
forensic_rc=$?
if [ "$forensic_rc" -ne 0 ]; then
    echo "ci_smoke: forensics (sync) FAILED (rc=$forensic_rc)"
fi

echo "== ci_smoke: NaN forensics (deferred async window, PT_NAN_POLL=8) =="
# the same gate with the deferred verdict: the trip only surfaces at an
# 8-step poll boundary, so the forensic step walk must localize the
# poison INSIDE the condemned window before the op/row bisection
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 PT_ASYNC=1 \
    PT_NAN_POLL=8 PT_FAULT="nan_step:at=5:row=3" \
    python tools/fault_soak.py --steps 16 --ckpt "$forensic_dir/ckpt2" \
    --expect-forensics --expect-async
forensic_async_rc=$?
if [ "$forensic_async_rc" -ne 0 ]; then
    echo "ci_smoke: forensics (async) FAILED (rc=$forensic_async_rc)"
fi
rm -rf "$forensic_dir"

echo "== ci_smoke: pod soak (sharded ckpt, kill-and-resume, reshard) =="
# pod-resilience gate (docs/robustness.md): two sharded-checkpoint
# trainers over one directory; wave 1 SIGKILLs a worker mid-run (the
# survivor must exit RESTART_EXIT_CODE via the health watchdog), wave 2
# arms the device_loss fault site (a worker goes silent and wedges; the
# peer must trip, roll back to the last good manifest, and request a
# restart; the supervisor reaps exactly the wedged host), wave 3
# restarts on the SMALLER roster and must elastically reshard
# (--expect-resume --expect-reshard) and finish with losses bitwise
# equal to an uninterrupted single-host run.  Zero orphaned tmp/.parts
# dirs and >= 1 health-trip flight dump are asserted by the tool.
pod_dir=$(mktemp -d /tmp/pt_pod.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 \
    python tools/pod_soak.py --workers 2 --steps 30 --dir "$pod_dir" \
    --expect-resume --expect-reshard
pod_rc=$?
if [ "$pod_rc" -ne 0 ]; then
    echo "ci_smoke: pod soak FAILED (rc=$pod_rc)"
fi
rm -rf "$pod_dir"

echo "== ci_smoke: serving soak (continuous batching under chaos) =="
# serving gate (docs/serving.md): serve_soak drives a real
# Predictor-backed ServingEngine with closed+open-loop traffic while
# four fault sites are armed — slow batches, consecutive dispatch
# failures (the breaker must trip AND recover), a compile-cache-miss
# storm, and a mid-run SIGTERM that must turn into a graceful drain.
# --assert-slo fails the gate unless p99 is finite, every admitted
# request got a terminal reply (admitted == completed + errors +
# deadline_exceeded + shed), serving.deadlocks == 0, and the shed rate
# stays under the ceiling.
#
# Observability gates ride the same soak (docs/observability.md):
#   --trace-out      exported Perfetto trace must decompose a request
#                    into queue/dispatch/device child spans linked to
#                    its batch span, covering >= 90% of its latency
#   --metrics-port   /metrics scraped mid-run (serving_admitted_total
#                    present) and post-drain (accounting identity holds
#                    in the scraped values)
#   --expect-flight  the serve_dispatch mid-batch crash must leave a
#                    flight dump holding that batch's span + the
#                    fault.injected event (PT_FLIGHT_DIR below)
flight_dir=$(mktemp -d /tmp/pt_flight.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=0 \
    PT_FLIGHT_DIR="$flight_dir" \
    PT_FAULT="serve_slow_batch:at=1:times=1:s=0.05,serve_dispatch:at=2:times=3,compile_storm:at=12:times=3:s=0.03,queue_overflow:at=30:times=2,sigterm:at=70" \
    python tools/serve_soak.py --requests 80 --qps 150 --clients 2 \
    --deadline-ms 4000 --shed-ceiling 0.35 \
    --assert-slo --expect-breaker --expect-drain \
    --trace-out "$flight_dir/soak_trace.json" --metrics-port 0 \
    --expect-flight
serve_rc=$?
if [ "$serve_rc" -ne 0 ]; then
    echo "ci_smoke: serving soak FAILED (rc=$serve_rc)"
fi
rm -rf "$flight_dir"

echo "== ci_smoke: decode soak (streaming generation under chaos) =="
# generation gate (docs/generation.md): serve_soak --scenario decode
# drives a GenerationEngine over the PAGED KV pool with every density
# multiplier armed — int8-quantized pages (PT_KV_QUANT), shared-prefix
# caching (the prompts open with one full shared page), speculative
# draft/verify decoding — with open-loop traffic of mixed prompt
# lengths, mid-soak cancellations, periodic overlong prompts (must be
# REFUSED, never truncated), and a decode_step fault that must turn
# into clean error replies while the engine keeps serving.
# --assert-slo fails the gate unless the accounting identity holds
# (terminal == admitted), serving.deadlocks == 0, TTFT/ITL histograms
# are populated, at least one mixed prefill+decode dispatch happened,
# zero compiles landed after warmup (the fused window executables are
# closed over page GEOMETRY, never per-request block tables), the
# prefix cache actually hit (prefix_hits > 0), speculation actually
# accepted tokens (spec_accepted > 0), and every KV slot AND page is
# back on the free list after drain.  --capacity-floor then reruns a
# fixed 16 KiB page budget with an oversubscribed slot table: excess
# streams must queue at admission backpressure (never die mid-stream
# as kv_oom) while >= 8 concurrent streams hold SLO — 4x what the
# dense PR-11 layout fits in the same bytes.  PT_CACHE=1 so the
# decode/prefill/verify executables round-trip the persistent AOT
# cache on repeat runs.
decode_cache=$(mktemp -d /tmp/pt_decode_cache.XXXXXX)
timeout -k 10 600 env JAX_PLATFORMS=cpu PT_CACHE=1 \
    PT_CACHE_DIR="$decode_cache" \
    PT_FAULT="decode_step:at=3" PT_KV_QUANT=int8 \
    python tools/serve_soak.py --scenario decode --requests 40 --qps 60 \
    --assert-slo --speculative --page-len 4 --kv-quant int8 \
    --capacity-floor 8
decode_rc=$?
if [ "$decode_rc" -ne 0 ]; then
    echo "ci_smoke: decode soak FAILED (rc=$decode_rc)"
fi
rm -rf "$decode_cache"

echo "== ci_smoke: tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
t1_rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"

echo "== ci_smoke: bench.py JSON schema + warm-start =="
# tiny shapes: the smoke validates the schema, not the throughput.
# Two runs over one fresh PT_CACHE_DIR: the first is cold and populates
# the persistent compile cache, the second must WARM-START — disk cache
# hits > 0 and compile seconds collapsing (core/compile_cache.py).
smoke_cache=$(mktemp -d /tmp/pt_smoke_cache.XXXXXX)
trap 'rm -rf "$smoke_cache"' EXIT
# BENCH_ALLOW_CPU=1: bench.py hard-exits on a non-TPU backend unless the
# caller explicitly opts into a CPU smoke (this IS the CPU smoke);
# PT_STRICT_KERNELS=1: any generated kernel silently degrading to the
# replay fails the bench run itself, not just the counter check below
# smoke MODEL dims, not just smoke B/T: the interpret-mode kernelgen
# tier pays per parameter, so the transformer-base 25M params (and
# resnet50's) would take minutes per step on CPU
bench_env="JAX_PLATFORMS=cpu BENCH_PROBE_TIMEOUT=60 BENCH_ALLOW_CPU=1 \
    BENCH_B=2 BENCH_T=16 BENCH_VOCAB=256 BENCH_LAYERS=2 BENCH_HEADS=2 \
    BENCH_DMODEL=32 BENCH_DINNER=64 BENCH_RESNET_B=1 \
    BENCH_RESNET_DEPTH=20 BENCH_RESNET_SET=cifar10 \
    BENCH_STEPS_PER_LAUNCH=2 \
    PT_STRICT_KERNELS=1 PT_CACHE=1 PT_CACHE_DIR=$smoke_cache"
# on failure the last stdout line is bench.py's structured
# {"error": ..., "stage": ...} tail — echo it so a dead round still
# leaves a diagnosable artifact in the CI log
bench_out=$(timeout -k 10 1200 env $bench_env python bench.py) \
    || { echo "ci_smoke: bench.py (cold) FAILED"; \
         echo "$bench_out" | tail -1; exit 1; }
echo "$bench_out"
bench_out2=$(timeout -k 10 1200 env $bench_env python bench.py) \
    || { echo "ci_smoke: bench.py (warm) FAILED"; \
         echo "$bench_out2" | tail -1; exit 1; }
echo "$bench_out2"

python - "$bench_out" "$bench_out2" <<'EOF'
import json
import sys

rec = json.loads(sys.argv[1].strip().splitlines()[-1])
rec2 = json.loads(sys.argv[2].strip().splitlines()[-1])
expected = [
    'metric', 'value', 'unit', 'vs_baseline', 'mfu', 'model_tflops_per_s',
    'params_m', 'matmul_params_m', 'backend', 'batch', 'seq', 'amp',
    'flash', 'steps_per_launch', 'single_step_tokens_per_sec',
    'sync_mode_tokens_per_sec', 'check_nan_overhead_x', 'telemetry',
]
missing = [k for k in expected if k not in rec]
if missing:
    sys.exit('ci_smoke: bench JSON is missing keys: %s' % missing)
if rec['metric'] != 'transformer_base_tokens_per_sec_per_chip':
    sys.exit('ci_smoke: unexpected headline metric %r' % rec['metric'])
if not rec['steps_per_launch'] > 1:
    sys.exit('ci_smoke: headline must run the fused multi-step loop '
             '(steps_per_launch=%r)' % rec['steps_per_launch'])
if not (isinstance(rec['value'], (int, float)) and rec['value'] > 0):
    sys.exit('ci_smoke: bad headline value %r' % rec['value'])

tel = rec['telemetry']
tel_expected = ['platform', 'device_kind', 'retraces', 'retraces_total',
                'compiles', 'compile_s', 'compile_s_cold', 'compile_s_warm',
                'compile_cache_hits', 'compile_cache_misses', 'tail_splits',
                'emit_s', 'trace_s', 'backend_compile_s',
                'program_op_count_raw', 'program_op_count_opt',
                'opt_pass_ms', 'opt_ops_fused', 'stall_count',
                'prefetch_starvation_s', 'fetch_sync_s',
                'kernel_fallbacks', 'emitter_fallbacks',
                'kernelgen_ops', 'kernelgen_fallbacks',
                'autotune_searches', 'autotune_cache_hits', 'fused_adam_ms',
                'host_blocked_s', 'nan_poll_lag_steps',
                'prefetch_upload_overlap_s', 'forensics_replays',
                'quarantined_samples']
tel_missing = [k for k in tel_expected if k not in tel]
if tel_missing:
    sys.exit('ci_smoke: telemetry block is missing keys: %s' % tel_missing)

# shared-schema contract (observability/export.py): ALL three emitters
# (bench.py, serve_soak.py, fault_soak.py) print sections of one SCHEMA
# table — validate the declarative table itself once, here
from paddle_tpu.observability import export as obs_export
if obs_export.schema_keys('bench') != tel_expected:
    sys.exit('ci_smoke: SCHEMA["bench"] drifted from the expected '
             'telemetry keys: %r' % (obs_export.schema_keys('bench'),))
for section, need in (('serving', ('admitted', 'terminal_replies',
                                   'shed_rate', 'p50_ms', 'p99_ms',
                                   'ttft_p50_ms', 'ttft_p99_ms',
                                   'itl_p50_ms', 'itl_p99_ms',
                                   'kv_slots_in_use', 'counters')),
                      ('resilience', ('counters',))):
    have = obs_export.schema_keys(section)
    absent = [k for k in need if k not in have]
    if absent:
        sys.exit('ci_smoke: SCHEMA[%r] is missing keys %s'
                 % (section, absent))
if not tel['platform']:
    sys.exit('ci_smoke: telemetry.platform is empty — the bench no longer '
             'self-labels the backend it ran on')
for label, t in (('cold', tel), ('warm', rec2['telemetry'])):
    if t['retraces'] > 0:
        sys.exit('ci_smoke: %s bench reports %d retrace(s) AFTER warmup — '
                 'the fused loop recompiled mid-measurement (retrace '
                 'regression)' % (label, t['retraces']))
if tel['kernel_fallbacks'] > 0:
    sys.exit('ci_smoke: %d kernel fallback(s) — a pallas kernel silently '
             'degraded to its composed path (PT_STRICT_KERNELS=1 shows '
             'the raw error)' % tel['kernel_fallbacks'])
# kernelgen gate, bench face (docs/kernels.md): PT_KERNELGEN=1 is the
# bench default, so generated kernels must actually engage and never
# silently un-fuse back to the replay
for label, t in (('cold', tel), ('warm', rec2['telemetry'])):
    if t['kernelgen_fallbacks'] > 0:
        sys.exit('ci_smoke: %s bench reports %d kernelgen fallback(s) — '
                 'a fused group silently degraded from its generated '
                 'kernel to the replay (PT_STRICT_KERNELS=1 shows the '
                 'raw error)' % (label, t['kernelgen_fallbacks']))
if not tel['kernelgen_ops'] > 0:
    sys.exit('ci_smoke: cold bench kernelgen_ops=%r — PT_KERNELGEN=1 is '
             'the bench default but no fused group lowered through a '
             'generated kernel' % tel['kernelgen_ops'])
# autotuner, bench face (docs/kernels.md): the cold run pays block-size
# searches; the warm run serves every plan (or every block choice) from
# the persistent cache and must never re-search.  autotune_cache_hits is
# NOT asserted here: a fully-warm AOT cache never rebuilds plans, so the
# dedicated autotune persistence gate above owns the disk-hit assertion.
if not tel['autotune_searches'] > 0:
    sys.exit('ci_smoke: cold bench autotune_searches=%r — PT_AUTOTUNE=1 '
             'is the default but no block-size search ran'
             % tel['autotune_searches'])
if rec2['telemetry']['autotune_searches'] != 0:
    sys.exit('ci_smoke: warm bench re-ran %d autotune search(es) — '
             'persisted choices (or AOT executables) were not honored'
             % rec2['telemetry']['autotune_searches'])
if tel['fused_adam_ms'] is not None and not tel['fused_adam_ms'] > 0:
    sys.exit('ci_smoke: fused_adam_ms=%r — the fused-Adam micro-bench '
             'did not produce a timing' % tel['fused_adam_ms'])
for label, t in (('cold', tel), ('warm', rec2['telemetry'])):
    if t['emitter_fallbacks'] > 0:
        sys.exit('ci_smoke: %s bench reports %d emitter fallback(s) — the '
                 'direct emitter degraded a bench program to traced '
                 'lowering (PT_STRICT_EMIT=1 shows the raw error)'
                 % (label, t['emitter_fallbacks']))
if tel['compiles'] < 1:
    sys.exit('ci_smoke: telemetry.compiles=%r — executor instrumentation '
             'recorded no compiles at all' % tel['compiles'])
if tel['tail_splits'] < 1:
    sys.exit('ci_smoke: tail_splits=%r — the ragged-tail superbatch did '
             'not route through the single-step executable'
             % tel['tail_splits'])
if not tel['program_op_count_opt'] < tel['program_op_count_raw']:
    sys.exit('ci_smoke: PT_OPT rewriter did not shrink the bench program '
             '(raw=%r opt=%r)' % (tel['program_op_count_raw'],
                                  tel['program_op_count_opt']))

# warm-start contract: second fresh process over the same PT_CACHE_DIR
# serves executables from disk instead of compiling them
tel2 = rec2['telemetry']
if tel2['compile_cache_hits'] < 1:
    sys.exit('ci_smoke: warm run reports compile_cache_hits=%r — the '
             'persistent executable cache missed across processes'
             % tel2['compile_cache_hits'])
if not tel2['compile_s'] < 0.5 * max(tel['compile_s'], 1e-9):
    sys.exit('ci_smoke: warm compile_s=%.3f did not drop vs cold=%.3f — '
             'warm start is not actually skipping compilation'
             % (tel2['compile_s'], tel['compile_s']))
# direct-emitter gate, part 2: PT_EMIT=1 is the bench default, so the
# cold run must show emitter seconds (the emitter actually engaged) and
# the warm fresh process must serve emitted executables from disk —
# emit_s + trace_s collapsing alongside compile_s proves the AOT cache
# keys emitted artifacts correctly (fingerprint extra=emitter coverage)
cold_front = tel['emit_s'] + tel['trace_s']
warm_front = tel2['emit_s'] + tel2['trace_s']
if not tel['emit_s'] > 0:
    sys.exit('ci_smoke: cold bench emit_s=%r — PT_EMIT=1 is the default '
             'but the direct emitter never engaged' % tel['emit_s'])
if not warm_front < 0.5 * max(cold_front, 1e-9):
    sys.exit('ci_smoke: warm emit_s+trace_s=%.3f did not collapse vs '
             'cold=%.3f — emitted executables are not round-tripping '
             'the persistent cache' % (warm_front, cold_front))
print('ci_smoke: bench JSON schema ok (%d keys, steps_per_launch=%d, '
      'platform=%s, retraces=%d after warmup)'
      % (len(rec), rec['steps_per_launch'], tel['platform'],
         tel['retraces']))
print('ci_smoke: warm start ok (cold compile_s=%.2f -> warm %.2f, '
      'hits=%d, load_s=%.2f)'
      % (tel['compile_s'], tel2['compile_s'], tel2['compile_cache_hits'],
         tel2['compile_s_warm']))
EOF
schema_rc=$?

echo "== ci_smoke: perf lab — scenario matrix, ledger, regression gate =="
# the full matrix at the SAME smoke geometry as the bench gate, into a
# throwaway ledger: every scenario must land a schema-valid record with
# non-null provenance (`check`), and `compare --fail-on regression`
# must come back green against the committed smoke baseline
# (PERF_BASELINE.json, blessed with this exact env — counters are
# zero-tolerance; timings ride the baseline's wide smoke tolerance).
# JAX_PLATFORMS=cpu marks the records a DELIBERATE cpu run (fallback
# null), so the committed cpu baseline compares instead of refusing.
perflab_ledger="$smoke_cache/perflab_ledger.jsonl"
perflab_env="JAX_PLATFORMS=cpu PT_KERNELGEN=1 PT_STRICT_KERNELS=1 \
    PT_CACHE=1 PT_CACHE_DIR=$smoke_cache \
    BENCH_B=2 BENCH_T=16 BENCH_VOCAB=256 BENCH_LAYERS=2 BENCH_HEADS=2 \
    BENCH_DMODEL=32 BENCH_DINNER=64 BENCH_RESNET_B=1 \
    BENCH_RESNET_DEPTH=20 BENCH_RESNET_SET=cifar10 \
    BENCH_STEPS_PER_LAUNCH=2 \
    PERFLAB_BEST_OF=2 PERFLAB_DECODE_REQUESTS=6 PERFLAB_POD_STEPS=4 \
    PERFLAB_RESNET_STEPS=2 PERFLAB_ADAM_STEPS=5 PERFLAB_LAUNCHES=2"
timeout -k 10 1800 env $perflab_env python tools/perflab.py run \
    --ledger "$perflab_ledger" --budget-s 420 \
    && env $perflab_env python tools/perflab.py check \
        --ledger "$perflab_ledger" \
    && env $perflab_env python tools/perflab.py compare \
        --ledger "$perflab_ledger" --baseline PERF_BASELINE.json \
        --fail-on regression
perflab_rc=$?
if [ "$perflab_rc" -ne 0 ]; then
    echo "ci_smoke: perflab gate FAILED (rc=$perflab_rc)"
fi

if [ "$t1_rc" -ne 0 ]; then
    echo "ci_smoke: tier-1 tests FAILED (rc=$t1_rc)"
fi
[ "$t1_rc" -eq 0 ] && [ "$schema_rc" -eq 0 ] && [ "$lint_rc" -eq 0 ] && \
    [ "$lint_schema_rc" -eq 0 ] && \
    [ "$ruff_rc" -eq 0 ] && [ "$opt_lint_rc" -eq 0 ] && \
    [ "$opt_gate_rc" -eq 0 ] && [ "$shard_rc" -eq 0 ] && \
    [ "$emit_zoo_rc" -eq 0 ] && \
    [ "$kg_zoo_rc" -eq 0 ] && [ "$autotune_rc" -eq 0 ] && \
    [ "$soak_rc" -eq 0 ] && \
    [ "$resume_rc" -eq 0 ] && [ "$async_rc" -eq 0 ] && \
    [ "$forensic_rc" -eq 0 ] && [ "$forensic_async_rc" -eq 0 ] && \
    [ "$pod_rc" -eq 0 ] && \
    [ "$serve_rc" -eq 0 ] && [ "$decode_rc" -eq 0 ] && \
    [ "$perflab_rc" -eq 0 ]
