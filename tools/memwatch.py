#!/usr/bin/env python
"""Device/host memory report: run a workload, print what it cost.

Answers the two questions the HBM-bound fusion work (ROADMAP item 2)
keeps asking:

  * what does one training/serving launch hold on the DEVICE —
    ``exec.hbm_peak_bytes`` / ``exec.hbm_in_use_bytes`` where the
    backend reports memory stats (TPU/GPU), ``exec.live_buffers``
    everywhere (a monotonically-climbing live count is a buffer leak);
  * what does checkpointing hold on the HOST —
    ``ckpt.snapshot_host_bytes`` per snapshot (forced device->host
    copies pinned until the async writer drains) against the process
    high-water RSS;
  * (``--decode``) what does the paged KV pool of the streaming decode
    runtime reserve vs actually pin — ``generation.kv_bytes_reserved``
    (the fixed pool footprint) against ``generation.kv_bytes_live`` /
    ``kv_pages_in_use`` sampled while streams run, the serving-density
    counterpart of the HBM gauges (docs/generation.md).

Runs a small fused training loop (the same shape bench.py uses) with
periodic checkpoints, sampling after every launch, and prints one JSON
report.  ``--steps``/``--steps-per-launch``/``--hidden`` scale the
workload; on CPU the HBM gauges are absent by design (memory_stats()
is a TPU/GPU surface) and the report says so instead of printing
zeros that look like measurements.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _decode_report(args):
    """Run a few streams through a small paged DecodeRuntime and sample
    the KV pool gauges: reserved (fixed) vs live (pages in use) bytes —
    the number the serving-density work optimizes."""
    import paddle_tpu.observability as obs
    from paddle_tpu.serving.generation import (DecodeRuntime,
                                               SamplingParams,
                                               random_weights)
    cfg = dict(vocab=128, d_model=32, n_layer=2, n_head=4, n_kv_head=2,
               d_ffn=64, theta=10000.0, max_len=32)
    rt = DecodeRuntime(random_weights(cfg, seed=0), cfg, slots=4,
                       prefill_chunk=4, kv_quant=args.kv_quant)
    rt.warmup(steps=4)

    def kv_gauges():
        g = obs.metrics_snapshot().get('gauges', {})
        return {k: g.get('generation.' + k)
                for k in ('kv_bytes_reserved', 'kv_bytes_live',
                          'kv_pages_in_use', 'kv_slots_in_use')}

    peak = {}
    slots = [rt.alloc_slot() for _ in range(rt.slots)]
    try:
        for i, slot in enumerate(slots):
            prompt = [1 + i, 5, 9, 2, 7, 3]
            start = rt.try_begin(slot, prompt, 4)
            for off in range(start, len(prompt), rt.prefill_chunk):
                rt.prefill(slot, prompt[off:off + rt.prefill_chunk], off,
                           SamplingParams(seed=i))
        import numpy as np
        active = np.ones(rt.slots, bool)
        zeros = np.zeros(rt.slots, np.int32)
        for _ in range(4):
            ok = all(rt.ensure_capacity(s, int(rt.host_len[s]) + 4)
                     for s in slots)
            if not ok:
                break
            rt.decode_window(4, active, zeros, zeros.astype(np.float32),
                             zeros)
        peak = kv_gauges()
    finally:
        for slot in slots:
            rt.free_slot(slot)
        if rt.prefix is not None:
            rt.prefix.reset()
    drained = kv_gauges()
    return {'quant': rt.cache.quant,
            'page_len': rt.cache.page_len,
            'page_bytes': rt.cache.page_bytes(),
            'dense_slot_bytes': rt.cache.dense_slot_bytes(),
            'peak': peak, 'drained': drained,
            'pages_leaked': rt.pool.in_use()}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=32)
    ap.add_argument('--steps-per-launch', type=int, default=4)
    ap.add_argument('--batch', type=int, default=16)
    ap.add_argument('--hidden', type=int, default=64)
    ap.add_argument('--ckpt-interval', type=int, default=8,
                    help='checkpoint every N steps (0 disables)')
    ap.add_argument('--decode', action='store_true',
                    help='also run a small paged decode workload and '
                         'report the KV pool gauges')
    ap.add_argument('--kv-quant', default=None, choices=['none', 'int8'],
                    help='KV quantization for the --decode workload '
                         '(default: env PT_KV_QUANT)')
    args = ap.parse_args()

    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import memory as obs_mem
    from paddle_tpu.train import CheckpointConfig, Checkpointer

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, args.hidden, act='relu')
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.reduce_mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    exe, scope = fluid.Executor(), fluid.Scope()
    rng = np.random.RandomState(5)
    K = max(1, args.steps_per_launch)

    def superfeed():
        return {'x': rng.rand(K, args.batch, 8).astype('float32'),
                'lbl': rng.randint(0, 4, (K, args.batch, 1)).astype('int64')}

    import tempfile
    ck = None
    samples = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        if args.ckpt_interval > 0:
            ck = Checkpointer(CheckpointConfig(
                checkpoint_dir=tempfile.mkdtemp(prefix='pt_memwatch.'),
                step_interval=args.ckpt_interval, handle_signals=False),
                exe)
        step = 0
        while step < args.steps:
            exe.run_steps(main_prog, feed_list=superfeed(), steps=K,
                          fetch_list=[loss.name], return_numpy=False)
            step += K
            if ck is not None:
                ck.maybe_save(0, step)
            g = obs.metrics_snapshot().get('gauges', {})
            samples.append({
                'step': step,
                'hbm_peak_bytes': g.get('exec.hbm_peak_bytes'),
                'hbm_in_use_bytes': g.get('exec.hbm_in_use_bytes'),
                'live_buffers': g.get('exec.live_buffers'),
                'ckpt_snapshot_host_bytes':
                    g.get('ckpt.snapshot_host_bytes'),
            })
        if ck is not None:
            ck.wait()

    g = obs.metrics_snapshot().get('gauges', {})
    c = obs.counters()
    hbm_samples = [s['hbm_peak_bytes'] for s in samples
                   if s['hbm_peak_bytes'] is not None]
    live = [s['live_buffers'] for s in samples
            if s['live_buffers'] is not None]
    report = {
        'device_stats_supported': bool(hbm_samples),
        'hbm_peak_bytes_max': max(hbm_samples) if hbm_samples else None,
        'hbm_limit_bytes': g.get('exec.hbm_limit_bytes'),
        'live_buffers_first': live[0] if live else None,
        'live_buffers_last': live[-1] if live else None,
        'ckpt_snapshot_host_bytes': g.get('ckpt.snapshot_host_bytes'),
        'ckpt_snapshot_bytes_total': int(
            c.get('ckpt.snapshot_bytes_total') or 0),
        'ckpt_saves': int(c.get('ckpt.saves') or 0),
        'host_rss_peak_bytes': obs_mem.host_rss_bytes(),
        'samples': samples,
    }
    if not hbm_samples:
        report['note'] = ('backend reports no memory_stats() (CPU): HBM '
                          'gauges are absent by design; live_buffers and '
                          'host accounting above are still real')
    if args.decode:
        report['kv'] = _decode_report(args)
    print(json.dumps(report))
    # a leak check cheap enough to always run: the live-buffer count at
    # the end of a steady-state loop should not have grown unboundedly
    if live and live[-1] > max(16, 4 * max(live[0], 1)):
        sys.exit('memwatch: live buffer count grew %d -> %d over the '
                 'run — buffer leak' % (live[0], live[-1]))
    return 0


if __name__ == '__main__':
    sys.exit(main())
