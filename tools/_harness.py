"""Shared bench/soak process harness: stage tracking, the structured
{"error", "stage"} JSON tail, the hang watchdog, and the subprocess
backend probe.

One implementation, one contract, five consumers (bench.py, perflab
children, fault_soak, serve_soak, pod_soak): whatever kills the process
— an exception, a hang, a hung PJRT init — the LAST stdout line is

    {"error": <kind>, "stage": <last stage entered>, "detail": ...}

so a dead round is still a diagnosable artifact instead of a bare
stack (or nothing).  Stdlib-only on purpose: bench.py must be able to
import this BEFORE importing jax/paddle_tpu, because the whole point of
the subprocess probe is to never init the device runtime in-process
until a child proved it responds.
"""
import json
import os
import subprocess
import sys
import threading
import traceback

# BENCH_PROBE_S is the documented knob (default 60s — a healthy PJRT
# init is seconds, and BENCH_r05 showed a hung one never recovers, so
# 300s only delayed the CPU fallback); BENCH_PROBE_TIMEOUT kept for
# back-compat.
PROBE_TIMEOUT_S = int(os.environ.get('BENCH_PROBE_S')
                      or os.environ.get('BENCH_PROBE_TIMEOUT') or '60')

_PROBE_CODE = r"""
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((128, 128), jnp.bfloat16)
s = float((x @ x).sum())
assert s == 128 * 128 * 128, s
print('PROBE_OK', d[0].platform, '|', d[0].device_kind)
"""

_TOOL = ['BENCH']
_STAGE = ['startup']


def set_tool(name):
    """Stage-line prefix, e.g. set_tool('PERFLAB') -> 'PERFLAB: stage=x'."""
    _TOOL[0] = name


def current_stage():
    return _STAGE[0]


def stage(name):
    _STAGE[0] = name
    print('%s: stage=%s' % (_TOOL[0], name), file=sys.stderr)


def emit_error(kind, detail, **extra):
    """The structured JSON death tail.  Extra keys (e.g. scenario=...)
    ride along so supervisors can attribute the failure."""
    rec = {'error': kind, 'stage': _STAGE[0], 'detail': str(detail)[:2000]}
    rec.update(extra)
    print(json.dumps(rec), flush=True)


def install_watchdog(default_s=1800.0, env='BENCH_WATCHDOG_S',
                     flight_tag=None, **extra):
    """A hung in-process compile/launch used to produce a DEAD round: no
    JSON, no diagnosis.  The watchdog emits the structured JSON tail
    naming the last stage entered, dumps every thread's stack to stderr,
    leaves a flight-recorder postmortem, and exits hard.  <env>=0
    disables.  Returns the timer (cancel it on clean exit) or None."""
    budget = float(os.environ.get(env, str(default_s)))
    if budget <= 0:
        return None

    def _trip():
        emit_error('watchdog expired after %.0fs' % budget,
                   'hung in stage %r' % _STAGE[0], **extra)
        try:
            import faulthandler
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            pass
        try:
            # a flight postmortem naming the hung stage (only if the
            # observability plane was imported — never import jax here)
            if 'paddle_tpu.observability.flight' in sys.modules:
                _flight = sys.modules['paddle_tpu.observability.flight']
                _flight.record(flight_tag or 'harness.watchdog',
                               stage=_STAGE[0], budget_s=budget)
                _flight.maybe_dump('watchdog')
        except Exception:
            pass
        os._exit(3)

    t = threading.Timer(budget, _trip)
    t.daemon = True
    t.start()
    return t


def probe_backend(retries=None, timeout_s=None):
    """Run a trivial device computation in a subprocess with a timeout.
    A failed/hung probe is retried once (BENCH_r05 lost a whole round to
    one transient 300s PJRT init hang).  Returns (platform, device_kind)
    or (None, reason)."""
    if retries is None:
        retries = int(os.environ.get('BENCH_PROBE_RETRIES', '1'))
    if timeout_s is None:
        timeout_s = PROBE_TIMEOUT_S
    reason = 'probe never ran'
    for attempt in range(retries + 1):
        try:
            r = subprocess.run([sys.executable, '-c', _PROBE_CODE],
                               capture_output=True, text=True,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            reason = 'probe timed out after %ds (PJRT init hang)' % \
                timeout_s
        else:
            for line in r.stdout.splitlines():
                if line.startswith('PROBE_OK'):
                    _, platform, _, kind = line.split(None, 3)
                    return platform, kind
            tail = (r.stderr or r.stdout).strip().splitlines()[-8:]
            reason = 'probe rc=%d: %s' % (r.returncode, ' | '.join(tail))
        if attempt < retries:
            print('%s: backend probe failed (%s) — retrying (%d/%d)'
                  % (_TOOL[0], reason, attempt + 1, retries),
                  file=sys.stderr)
    return None, reason


def main_guard(main, watchdog=True, watchdog_default_s=1800.0,
               watchdog_env='BENCH_WATCHDOG_S', flight_tag=None, **extra):
    """Run ``main()`` under the watchdog with the JSON-tail contract:
    an uncaught exception prints its traceback to stderr and the
    structured {"error", "stage"} line to stdout, then exits 1.
    SystemExit passes through untouched (soak SLO failures keep their
    messages and codes).  ``extra`` keys (e.g. scenario=...) ride along
    in the JSON tail.  Returns main()'s return code via sys.exit."""
    wd = install_watchdog(watchdog_default_s, env=watchdog_env,
                          flight_tag=flight_tag,
                          **extra) if watchdog else None
    try:
        rc = main()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 - structured JSON death
        traceback.print_exc()
        emit_error(type(e).__name__, e, **extra)
        sys.exit(1)
    finally:
        if wd is not None:
            wd.cancel()
    sys.exit(rc)
