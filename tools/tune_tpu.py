"""One-shot TPU tuning sweep for the headline benchmarks.

Run on a live chip (`python tools/tune_tpu.py`); prints a table of
(batch, seq) configurations for the transformer and batch sizes for
ResNet-50, so the best one can be promoted to bench.py defaults.  MFU
accounting and the chip peak are imported from bench.py — one metric,
two tools.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from bench import peak_flops  # noqa: E402


def _peak():
    import jax
    return peak_flops(jax.devices()[0].device_kind) or 197e12


def _sync(x):
    return float(np.asarray(x).ravel()[0])


def bench_transformer(B, T, steps=20):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=32000, trg_vocab=32000, max_len=T,
                           n_layer=6, n_head=8, d_model=512,
                           d_inner=2048, dropout=0.0, use_flash=True)
    main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = tr.synthetic_batch(np.random.RandomState(0), B, T)
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(3):
            loss, = exe.run(main, feed=feed, fetch_list=[out['loss']])
        _sync(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, = exe.run(main, feed=feed, fetch_list=[out['loss']],
                            return_numpy=False)
        _sync(loss)
        dt = time.perf_counter() - t0
    tps = steps * B * T / dt
    n_mm = sum(
        int(np.prod(v.shape)) for v in
        main.global_block().all_parameters()
        if v.shape and not v.name.endswith('_emb'))
    fpt = 6.0 * n_mm + 12.0 * T * 512 * (3 * 6)
    return tps, fpt * tps / _peak()


def bench_resnet(B, steps=10):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup, out, feed = resnet.bench_program(B=B)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(2):
            loss, = exe.run(main, feed=feed, fetch_list=[out['loss']])
        _sync(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, = exe.run(main, feed=feed, fetch_list=[out['loss']],
                            return_numpy=False)
        _sync(loss)
        dt = time.perf_counter() - t0
    ips = steps * B / dt
    from bench import RESNET50_TRAIN_FLOPS_PER_IMAGE
    return ips, RESNET50_TRAIN_FLOPS_PER_IMAGE * ips / _peak()


def main():
    import jax
    print('backend:', jax.default_backend(), jax.devices()[0].device_kind,
          flush=True)
    for B, T in ((32, 256), (64, 256), (128, 256), (64, 512)):
        try:
            t0 = time.time()
            tps, mfu = bench_transformer(B, T)
            print('transformer B=%-4d T=%-4d  %9.0f tok/s  mfu=%.3f  '
                  '(%.0fs)' % (B, T, tps, mfu, time.time() - t0),
                  flush=True)
        except Exception as e:
            print('transformer B=%d T=%d FAILED: %s' % (B, T, e),
                  flush=True)
    for B in (64, 128, 256):
        try:
            t0 = time.time()
            ips, mfu = bench_resnet(B)
            print('resnet50    B=%-4d         %9.1f img/s  mfu=%.3f  '
                  '(%.0fs)' % (B, ips, mfu, time.time() - t0), flush=True)
        except Exception as e:
            print('resnet50 B=%d FAILED: %s' % (B, e), flush=True)


if __name__ == '__main__':
    sys.exit(main())
