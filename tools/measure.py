"""One-shot measurement harnesses behind PERF.md's numbers.

    python tools/measure.py decompose     # step-time split by model surgery
    python tools/measure.py longctx       # llama long-context train steps
    python tools/measure.py attn          # pallas-vs-composed attention grad
    python tools/measure.py soak          # 500-step stability/convergence

Run on a live chip; every harness prints its table and exits.  These
are the scripts that produced the round-4 PERF.md sections — kept
runnable so future rounds re-measure instead of trusting stale numbers.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _sync(x):
    return np.asarray(x)


def _timed_loop(exe, main, feed, loss, steps=30):
    import jax
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for _ in range(3):
        o, = exe.run(main, feed=feed, fetch_list=[loss])
    _sync(o)
    t0 = time.perf_counter()
    for _ in range(steps):
        o, = exe.run(main, feed=feed, fetch_list=[loss],
                     return_numpy=False)
    _sync(o)
    return (time.perf_counter() - t0) / steps * 1e3


def decompose():
    """Forward / backward / optimizer / CE split (PERF.md
    'Step-time decomposition')."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.models import transformer as tr
    B, T, V = 32, 256, 32000
    feeds = tr.synthetic_batch(np.random.RandomState(0), B, T)

    def run(tag, build):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build()
        main.set_amp(True)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            ms = _timed_loop(exe, main, feeds, loss)
        print('%-28s %7.2f ms' % (tag, ms), flush=True)
        return ms

    def tf(**kw):
        out = tr.transformer(V, V, max_len=T, n_layer=6, n_head=8,
                             d_model=512, d_inner=2048, dropout=0.0,
                             use_flash=True, **kw)
        return out

    run('fwd only', lambda: tf(is_train=False)['loss'])

    def with_opt(opt):
        def build():
            out = tf()
            opt().minimize(out['loss'])
            return out['loss']
        return build
    run('fwd+bwd+SGD', with_opt(lambda: fluid.optimizer.SGD(1e-4)))
    run('fwd+bwd+Adam', with_opt(lambda: fluid.optimizer.Adam(1e-4)))

    def no_ce():
        out = tf()
        loss = layers.reduce_mean(out['logits'])
        fluid.optimizer.Adam(1e-4).minimize(loss)
        return loss
    run('fwd+bwd+Adam, no CE', no_ce)


def longctx():
    """llama long-context train steps (PERF.md 'Long-context llama')."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import llama
    cfg = dict(vocab=32000, d_model=1024, n_layer=8, n_head=16,
               n_kv_head=4, d_ffn=2816, theta=500000.0, max_len=4096)
    for T, B in ((4096, 2), (8192, 1)):
        c = dict(cfg, max_len=T)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = llama.build(c, lr=1e-4)
        main.set_amp(True)
        exe = fluid.Executor()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        feed = llama.make_batch(
            [rng.randint(3, 32000, (T + 1,)) for _ in range(B)], T)
        with fluid.scope_guard(scope):
            exe.run(startup)
            ms = _timed_loop(exe, main, feed, out['loss'], steps=10)
        print('llama T=%5d B=%d: %8.0f tok/s (%.1f ms/step)'
              % (T, B, B * T / ms * 1e3, ms), flush=True)


def attn():
    """pallas vs composed attention fwd+grad (PERF.md crossover table)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import attention as att
    rng = np.random.RandomState(0)

    def bench_grad(fn, args, iters=10):
        g = jax.jit(jax.grad(
            lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))
        out = g(*args)
        _sync(out[0][0, 0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(*args)
        _sync(out[0][0, 0, 0, 0])
        return (time.perf_counter() - t0) / iters * 1e3

    for T in (2048, 4096, 8192):
        q, k, v = (jnp.asarray(rng.randn(2, 8, T, 64), jnp.bfloat16)
                   for _ in range(3))
        att._FWD_PALLAS_MIN_T = 0
        att._BWD_PALLAS_SCORE_BYTES = 0
        tp = bench_grad(
            lambda q, k, v: att.flash_attention(q, k, v, causal=True),
            (q, k, v))
        att._FWD_PALLAS_MIN_T = 1 << 30
        tc = bench_grad(
            lambda q, k, v: att.flash_attention(q, k, v, causal=True),
            (q, k, v))
        print('T=%5d: pallas %7.2f ms   composed %7.2f ms' % (T, tp, tc),
              flush=True)


def soak():
    """500-step stability/convergence (PERF.md 'Sustained-training')."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tr
    B, T, V = 32, 128, 8000
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=V, trg_vocab=V, max_len=T, n_layer=4,
                           n_head=8, d_model=256, d_inner=1024,
                           dropout=0.1, lr=1.0, warmup_steps=400,
                           use_flash=True)
    main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)

    def copy_batch():
        rows = []
        for _ in range(B):
            n = rng.randint(T // 2, T - 1)
            s = rng.randint(3, V, (n,))
            rows.append((np.concatenate([s, [1]]),
                         np.concatenate([[0], s]),
                         np.concatenate([s, [1]])))
        return tr.make_batch(rows, T)

    pool = [{k: jax.device_put(v) for k, v in copy_batch().items()}
            for _ in range(50)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        t0 = time.perf_counter()
        for step in range(500):
            lv, = exe.run(main, feed=pool[step % 50],
                          fetch_list=[out['loss']], return_numpy=False)
            if (step + 1) % 100 == 0:
                print('step %d loss %.3f (%.1fs/100)' %
                      (step + 1, float(_sync(lv).ravel()[0]),
                       time.perf_counter() - t0), flush=True)
                t0 = time.perf_counter()


if __name__ == '__main__':
    harness = sys.argv[1] if len(sys.argv) > 1 else 'decompose'
    {'decompose': decompose, 'longctx': longctx,
     'attn': attn, 'soak': soak}[harness]()
