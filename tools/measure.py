"""Thin alias: the one-shot measurement harnesses moved into the perf
lab (`python tools/perflab.py probe <harness>`; implementation in
tools/_probes.py).  This shim keeps the old invocation working:

    python tools/measure.py decompose|longctx|attn|soak|hlo|convprobe|allreduce
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _probes  # noqa: E402

if __name__ == '__main__':
    sys.exit(_probes.probe_main())
