#!/usr/bin/env python
"""pt-lint — static analysis CLI over paddle_tpu programs.

Lints models saved via paddle_tpu.io (save_inference_model dirs) and the
bundled model zoo, without compiling anything:

    python tools/pt_lint.py path/to/saved_model_dir
    python tools/pt_lint.py --builtin mnist --builtin transformer
    python tools/pt_lint.py --all-builtin --min-severity warning
    python tools/pt_lint.py model_dir --json

Exit codes: 0 = no findings at/above --fail-on (default: error),
2 = gated findings present, 1 = usage or load failure.

docs/analysis.md documents the diagnostic codes and severities.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# lint must never touch an accelerator (and must run on CPU-only CI)
os.environ.setdefault('JAX_PLATFORMS', 'cpu')


def _fluid():
    import paddle_tpu as fluid
    return fluid


# --------------------------------------------------- bundled model zoo

def _zoo_entry(name):
    """name -> zero-arg builder returning (program, feed_names,
    fetch_names).  Builders construct into a fresh program pair so CLI
    invocations don't cross-contaminate the default program."""
    fluid = _fluid()
    import paddle_tpu.models as M

    builders = {
        'mnist': lambda: M.mnist.build(),
        'resnet': lambda: M.resnet.build(),
        'vgg': lambda: M.vgg.build(),
        'se_resnext': lambda: M.se_resnext.build(),
        'stacked_lstm': lambda: M.stacked_lstm.build(),
        'transformer': lambda: M.transformer.build(),
        'ctr_deepfm': lambda: M.ctr.deepfm(),
        'ctr_wide_deep': lambda: M.ctr.wide_deep(),
        'word2vec': lambda: M.word2vec.build(),
        'fit_a_line': lambda: M.simple.fit_a_line(),
        'recommender': lambda: M.simple.recommender(),
        'llama': lambda: M.llama.build(),
        'llama_prefill': lambda: M.llama.generation_program(
            mode='prefill'),
        'llama_decode': lambda: M.llama.generation_program(
            mode='decode'),
    }
    if name not in builders:
        raise KeyError('unknown builtin %r (have: %s)'
                       % (name, ', '.join(sorted(builders))))

    def build():
        prog, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, start):
            m = builders[name]()
        feeds = [v.name for v in m.get('feeds', ())]
        fetches = []
        for key in ('loss', 'accuracy', 'predict'):
            v = m.get(key)
            if v is not None:
                fetches.append(v.name)
        for v in m.get('fetches', ()):
            fetches.append(v if isinstance(v, str) else v.name)
        return prog, feeds, fetches

    return build


def builtin_names():
    return ['mnist', 'resnet', 'vgg', 'se_resnext', 'stacked_lstm',
            'transformer', 'ctr_deepfm', 'ctr_wide_deep', 'word2vec',
            'fit_a_line', 'recommender', 'llama', 'llama_prefill',
            'llama_decode']


# --------------------------------------------------- saved-model loading

def _load_saved(dirname, model_filename=None):
    from paddle_tpu import io as fluid_io
    path = os.path.join(dirname, model_filename or '__model__.json')
    with open(path) as f:
        desc = json.load(f)
    program = fluid_io.desc_to_program(desc)
    return (program, list(desc.get('feed_names', ())),
            list(desc.get('fetch_names', ())))


# --------------------------------------------------- linting + reporting

def _lint_one(label, build_fn, args):
    fluid = _fluid()
    try:
        program, feeds, fetches = build_fn()
    except Exception as e:  # noqa: BLE001 - reported, exit 1
        return label, None, None, 'load/build failed: %s' % e
    bucketer = None
    if args.seq_names or args.bucketed:
        bucketer = fluid.FeedBucketer(mask_name='__mask__',
                                      seq_names=args.seq_names or ())
    result = program.lint(feed_names=feeds, fetch_list=fetches,
                          bucketer=bucketer, optimize=args.optimize)
    plan = None
    if args.memplan:
        plan = getattr(program, '_last_memplan', None)
        if plan is None:  # memplan pass filtered out via passes=
            from paddle_tpu.analysis.passes.memplan import plan_memory
            plan = plan_memory(program, feed_names=feeds,
                               fetch_names=fetches)
    return label, result, plan, None


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='pt-lint', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('model_dirs', nargs='*',
                    help='saved-model dirs (paddle_tpu.io layout)')
    ap.add_argument('--builtin', action='append', default=[],
                    metavar='NAME',
                    help='lint a bundled paddle_tpu.models program '
                         '(repeatable); see --list-builtin')
    ap.add_argument('--all-builtin', action='store_true',
                    help='lint every bundled model program')
    ap.add_argument('--list-builtin', action='store_true',
                    help='print builtin names and exit')
    ap.add_argument('--model-filename', default=None,
                    help='program json inside a saved-model dir '
                         '(default __model__.json)')
    ap.add_argument('--min-severity', default='warning',
                    choices=['info', 'warning', 'error'],
                    help='lowest severity to PRINT (default warning)')
    ap.add_argument('--fail-on', default='error',
                    choices=['info', 'warning', 'error'],
                    help='exit 2 when findings at/above this severity '
                         'exist (default error)')
    ap.add_argument('--json', action='store_true',
                    help='emit one JSON object instead of text')
    ap.add_argument('--memplan', action='store_true',
                    help='also report the static per-device memory plan '
                         '(params + optimizer state + activation peak + '
                         'kv pool; docs/analysis.md) per target')
    ap.add_argument('--seq-names', action='append', default=[],
                    metavar='FEED',
                    help='assume a FeedBucketer covering this sequence '
                         'feed (repeatable; informs the retrace pass)')
    ap.add_argument('--bucketed', action='store_true',
                    help='assume a FeedBucketer pads the batch dim')
    ap.add_argument('--optimize', action='store_true',
                    help='run the PT_OPT rewriter pipeline (core/passes, '
                         'honoring PT_OPT_SKIP) first and lint the '
                         'OPTIMIZED program — what the executor actually '
                         'traces under PT_OPT=1; diagnostics still name '
                         'model source lines (docs/passes.md)')
    args = ap.parse_args(argv)

    if args.list_builtin:
        print('\n'.join(builtin_names()))
        return 0

    targets = []
    for d in args.model_dirs:
        targets.append((d, lambda d=d: _load_saved(
            d, model_filename=args.model_filename)))
    for name in (builtin_names() if args.all_builtin else args.builtin):
        targets.append(('builtin:%s' % name, _zoo_entry(name)))
    if not targets:
        ap.error('nothing to lint: pass saved-model dirs, --builtin, '
                 'or --all-builtin')

    gated = 0
    load_failed = 0
    out = {}
    for label, build_fn, in targets:
        label, result, plan, err = _lint_one(label, build_fn, args)
        if err is not None:
            load_failed += 1
            if args.json:
                out[label] = {'error': err}
            else:
                print('== %s\n  %s' % (label, err))
            continue
        gated += len(result.at_least(args.fail_on))
        if args.json:
            out[label] = result.to_dict()
            if plan is not None:
                out[label]['memplan'] = plan.to_dict()
        else:
            print('== %s' % label)
            text = result.render(args.min_severity)
            if plan is not None:
                text += '\n' + plan.render_table()
            print('\n'.join('  ' + line for line in text.split('\n')))
    if args.json:
        print(json.dumps({'fail_on': args.fail_on, 'results': out},
                         indent=2, sort_keys=True))
    if load_failed:
        return 1
    return 2 if gated else 0


if __name__ == '__main__':
    sys.exit(main())
