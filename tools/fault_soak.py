#!/usr/bin/env python
"""Fault-injection soak: a short training run that SURVIVES the armed
PT_FAULT matrix and proves it with counters.

Drives the full resilience stack end-to-end — async Checkpointer (+
signal flush), RecoveryPolicy rollback, FeedPrefetcher, run_steps fused
launches, the executor's fused check_nan verdict — under whatever faults
the caller armed via PT_FAULT (see paddle_tpu/testing/faults.py for the
site table).  Used by tools/ci_smoke.sh:

  phase 1: in-process faults (nan_step, ckpt_write, cache_read,
           cache_write, prefetch_stall) — must COMPLETE, with
           recovery.rollbacks > 0, faults.injected > 0, all losses
           finite, zero post-recovery retraces, zero pipeline stalls
           (--assert-recovery);
  phase 2: PT_FAULT=sigterm:at=K kills the process mid-run (the signal
           handler flushes a final checkpoint); a second invocation with
           --expect-resume must restore it and finish the run;
  phase 3: PT_ASYNC=1 PT_NAN_POLL=N re-runs phase 1 fully async —
           FetchFuture launches, deferred nan verdict — and
           --expect-async requires >=1 verdict poll AND >=1 deferred
           trip with zero steady-state stalls.

Prints one JSON line: {"steps_done": ..., "start": ..., "counters": ...}.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=12)
    ap.add_argument('--launch-k', type=int, default=2)
    ap.add_argument('--ckpt', required=True)
    ap.add_argument('--assert-recovery', action='store_true',
                    help='require rollbacks>0, injections>0, zero '
                         'post-recovery retraces, zero pipeline stalls')
    ap.add_argument('--expect-resume', action='store_true',
                    help='require a valid checkpoint to resume from')
    ap.add_argument('--expect-async', action='store_true',
                    help='require the deferred-nan async mode (nan_poll>1) '
                         'with >=1 verdict poll and >=1 deferred trip')
    args = ap.parse_args()

    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.data_feeder import FeedPrefetcher
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.train import (CheckpointConfig, Checkpointer,
                                  RecoveryPolicy)

    _flight.install()   # an uncaught crash still leaves a postmortem

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 17
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, 0.2)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    main_prog.set_amp(True)

    def feed_at(i):
        rng = np.random.RandomState(1000 + i)
        return {'x': rng.rand(8, 8).astype('float32'),
                'lbl': rng.randint(0, 4, (8, 1)).astype('int64')}

    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    ck = Checkpointer(CheckpointConfig(args.ckpt, step_interval=1,
                                       max_num_checkpoints=3),
                      exe, main_prog, scope=scope)
    ck.install_signal_handlers()
    meta = ck.restore()
    start = meta['step_id'] + 1 if meta else 0
    if args.expect_resume and (meta is None or start < 1):
        sys.exit('fault_soak: --expect-resume but no valid checkpoint '
                 'found in %s (meta=%r)' % (args.ckpt, meta))

    policy = RecoveryPolicy(ck, max_retries=4)
    K = args.launch_k
    # PT_ASYNC=1 / PT_NAN_POLL>1 puts the soak in the fully-async mode:
    # launches return FetchFuture handles, the fused all-finite verdict
    # accumulates on device, and losses only land on the host after a
    # CLEAN poll — a deferred trip condemns (drops) the whole window
    use_async = exe.nan_poll > 1
    pf = FeedPrefetcher((feed_at(i) for i in range(start, args.steps)),
                        steps=K, to_device=False)
    losses = []
    skipped = 0
    pending = []          # [(loss_future, k)] awaiting a clean verdict
    retrace_mark = None   # executor.retraces at the first rollback
    stall_mark = None     # executor.stall_count once steady state begins

    def flush_pending():
        for f, _ in pending:
            losses.extend(float(v) for v in np.asarray(f).ravel())
        del pending[:]

    with fluid.scope_guard(scope):
        if meta is None:
            exe.run(startup)
            # restore point BEFORE any step: recovery can roll back even
            # a first-step divergence
            ck.save(0, -1)
            ck.wait()
        step = start
        for stacked, k in pf:
            out = policy.run(lambda: exe.run_steps(
                main_prog, feed_list=stacked, steps=k, fetch_list=[loss],
                as_futures=use_async))
            if stall_mark is None:
                # steady state starts AFTER the first fused launch: the
                # cold-start gap (startup program, initial blocking save,
                # the injected prefetch_stall fault) is not what the
                # async-checkpointing stall budget is about
                stall_mark = int(
                    obs.counters().get('executor.stall_count') or 0)
            if out is None:
                # rolled back: steps pending a verdict were computed on
                # the now-condemned window — drop them with the rollback
                dropped = sum(n for _, n in pending)
                del pending[:]
                skipped += k + dropped
                step += k
                # everything after a rollback must reuse the cached
                # executables: restored numpy params have identical
                # specs, so ANY retrace from here on is a regression
                if retrace_mark is None:
                    retrace_mark = int(
                        obs.counters().get('executor.retraces') or 0)
                continue
            if use_async:
                pending.append((out[0], k))
                if exe.nan_clean():
                    # verdict window just polled clean: everything
                    # buffered is good — land it and checkpoint
                    flush_pending()
                    ck.maybe_save(0, step + k - 1)
            else:
                losses.extend(float(v) for v in np.asarray(out[0]).ravel())
                ck.maybe_save(0, step + k - 1)
            step += k
        if use_async and pending:
            # end of stream with verdicts still on device: force the poll
            # (through recovery, so a late trip rolls back cleanly)
            def drain():
                exe.poll_nan()
                return []
            tail = policy.run(drain)
            if tail is None:
                skipped += sum(n for _, n in pending)
                del pending[:]
            else:
                flush_pending()
                ck.maybe_save(0, step - 1)
        ck.wait()
    c = obs.counters()
    retraces_after_recovery = 0 if retrace_mark is None else \
        int(c.get('executor.retraces') or 0) - retrace_mark
    steady_stalls = 0 if stall_mark is None else \
        int(c.get('executor.stall_count') or 0) - stall_mark

    rec = {
        'start': start,
        'steps_done': len(losses),
        'steps_skipped': skipped,
        'losses_finite': bool(np.all(np.isfinite(losses))),
        # shared schema: observability/export.py SCHEMA['resilience']
        'counters': obs.telemetry_snapshot('resilience',
                                           snapshot=c)['counters'],
        'retraces_after_recovery': retraces_after_recovery,
        'steady_state_stalls': steady_stalls,
    }
    print(json.dumps(rec))

    if not rec['losses_finite']:
        sys.exit('fault_soak: non-finite loss escaped the recovery policy')
    if args.assert_recovery:
        cc = rec['counters']
        if cc['faults.injected'] < 1:
            sys.exit('fault_soak: no faults injected — PT_FAULT matrix '
                     'not armed?')
        if cc['recovery.rollbacks'] < 1:
            sys.exit('fault_soak: no rollbacks — the nan_step fault did '
                     'not exercise recovery')
        if rec['retraces_after_recovery'] > 0:
            sys.exit('fault_soak: %d retrace(s) after rollback — restored '
                     'state no longer matches the compiled executables'
                     % rec['retraces_after_recovery'])
        if rec['steady_state_stalls'] > 0:
            sys.exit('fault_soak: %d steady-state pipeline stall(s) — '
                     'async checkpointing (or recovery) is blocking the '
                     'step loop' % rec['steady_state_stalls'])
    if args.expect_async:
        cc = rec['counters']
        if exe.nan_poll <= 1:
            sys.exit('fault_soak: --expect-async but nan_poll=%d — set '
                     'PT_ASYNC=1 or PT_NAN_POLL>1' % exe.nan_poll)
        if cc['nan_poll.polls'] < 1:
            sys.exit('fault_soak: --expect-async but the deferred verdict '
                     'was never polled')
        if cc['nan_poll.trips'] < 1:
            sys.exit('fault_soak: --expect-async but no deferred trip — '
                     'the nan_step fault did not exercise the window')
    return 0


if __name__ == '__main__':
    sys.exit(main())
