#!/usr/bin/env python
"""Fault-injection soak: a short training run that SURVIVES the armed
PT_FAULT matrix and proves it with counters.

Drives the full resilience stack end-to-end — async Checkpointer (+
signal flush), RecoveryPolicy rollback, FeedPrefetcher, run_steps fused
launches, the executor's fused check_nan verdict — under whatever faults
the caller armed via PT_FAULT (see paddle_tpu/testing/faults.py for the
site table).  Used by tools/ci_smoke.sh:

  phase 1: in-process faults (nan_step, ckpt_write, cache_read,
           cache_write, prefetch_stall) — must COMPLETE, with
           recovery.rollbacks > 0, faults.injected > 0, all losses
           finite, zero post-recovery retraces, zero pipeline stalls
           (--assert-recovery);
  phase 2: PT_FAULT=sigterm:at=K kills the process mid-run (the signal
           handler flushes a final checkpoint); a second invocation with
           --expect-resume must restore it and finish the run;
  phase 3: PT_ASYNC=1 PT_NAN_POLL=N re-runs phase 1 fully async —
           FetchFuture launches, deferred nan verdict — and
           --expect-async requires >=1 verdict poll AND >=1 deferred
           trip with zero steady-state stalls;
  phase 4: PT_FAULT=nan_step:at=N:row=R with --expect-forensics arms a
           single poisoned batch row; the forensic pipeline
           (train/forensics.py) must name the exact (step, op, row),
           quarantine the sample, HEAL the window by replay, and the
           surviving losses must be bitwise-identical to an in-process
           uninjected reference run over the same quarantine.

Prints one JSON line: {"steps_done": ..., "start": ..., "counters": ...}.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _harness  # noqa: E402 - shared stage/watchdog/JSON-tail contract

BATCH = 8


def build_model(fluid):
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 17
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, 0.2)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    main_prog.set_amp(True)
    return main_prog, startup, loss


def feed_at(i):
    import numpy as np
    rng = np.random.RandomState(1000 + i)
    return {'x': rng.rand(BATCH, 8).astype('float32'),
            'lbl': rng.randint(0, 4, (BATCH, 1)).astype('int64')}


def reference_losses(fluid, quarantine_state, steps, launch_k):
    """Uninjected in-process reference: same model/seed/feeds/launch
    structure, the forensic run's quarantine pre-seeded — the bitwise
    yardstick the healed run must match on surviving samples."""
    import numpy as np
    from paddle_tpu.data_feeder import SampleQuarantine
    from paddle_tpu.testing import faults
    faults.configure('')     # neutralize the armed PT_FAULT matrix
    q = SampleQuarantine()
    q.restore(quarantine_state)
    main_prog, startup, loss = build_model(fluid)
    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    losses = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = 0
        while step < steps:
            k = min(launch_k, steps - step)
            per = [feed_at(step + j) for j in range(k)]
            stacked = {n: np.stack([f[n] for f in per]) for n in per[0]}
            stacked, _ = q.apply(stacked, step, k)
            out = exe.run_steps(main_prog, feed_list=stacked, steps=k,
                                fetch_list=[loss])
            for j, v in enumerate(np.asarray(out[0]).ravel()):
                losses[step + j] = float(v)
            step += k
    return losses


def first_consumer_of(program, var_name):
    """The op type the forensic report must name: the first program op
    reading ``var_name`` (its output is the first non-finite value a
    poisoned feed can produce)."""
    for op in program.global_block().ops:
        for names in op.inputs.values():
            seq = names if isinstance(names, (list, tuple)) else [names]
            if var_name in seq:
                return op.type
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=12)
    ap.add_argument('--launch-k', type=int, default=2)
    ap.add_argument('--ckpt', required=True)
    ap.add_argument('--assert-recovery', action='store_true',
                    help='require rollbacks>0, injections>0, zero '
                         'post-recovery retraces, zero pipeline stalls')
    ap.add_argument('--expect-resume', action='store_true',
                    help='require a valid checkpoint to resume from')
    ap.add_argument('--expect-async', action='store_true',
                    help='require the deferred-nan async mode (nan_poll>1) '
                         'with >=1 verdict poll and >=1 deferred trip')
    ap.add_argument('--expect-forensics', action='store_true',
                    help='require the armed nan_step:at=N:row=R fault to '
                         'be bisected to the exact (step, op, row), the '
                         'sample quarantined, the window healed by '
                         'replay, and the surviving losses bitwise-equal '
                         'to an uninjected reference run')
    args = ap.parse_args()

    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.observability as obs
    from paddle_tpu.data_feeder import FeedPrefetcher, SampleQuarantine
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.testing import faults
    from paddle_tpu.train import (CheckpointConfig, Checkpointer,
                                  LaunchRecord, RecoveryPolicy)

    _flight.install()   # an uncaught crash still leaves a postmortem

    _harness.stage('build')
    main_prog, startup, loss = build_model(fluid)

    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    quarantine = SampleQuarantine()
    ck = Checkpointer(CheckpointConfig(args.ckpt, step_interval=1,
                                       max_num_checkpoints=3),
                      exe, main_prog, scope=scope, quarantine=quarantine)
    ck.install_signal_handlers()
    meta = ck.restore()
    start = meta['step_id'] + 1 if meta else 0
    if args.expect_resume and (meta is None or start < 1):
        sys.exit('fault_soak: --expect-resume but no valid checkpoint '
                 'found in %s (meta=%r)' % (args.ckpt, meta))

    policy = RecoveryPolicy(ck, max_retries=4)
    K = args.launch_k
    # PT_ASYNC=1 / PT_NAN_POLL>1 puts the soak in the fully-async mode:
    # launches return FetchFuture handles, the fused all-finite verdict
    # accumulates on device, and losses only land on the host after a
    # CLEAN poll — a deferred trip condemns (drops) the whole window
    use_async = exe.nan_poll > 1
    pf = FeedPrefetcher((feed_at(i) for i in range(start, args.steps)),
                        steps=K, to_device=False)
    losses = {}           # step id -> loss (insertion order = land order)
    skipped = 0
    healed = 0            # steps recovered by the quarantine-replay rung
    pending = []          # [(loss_future, k, step0)] awaiting a verdict
    retrace_mark = None   # executor.retraces at the first rollback
    stall_mark = None     # executor.stall_count once steady state begins

    def flush_pending():
        for f, k, s0 in pending:
            for j, v in enumerate(np.asarray(f).ravel()):
                losses[s0 + j] = float(v)
        del pending[:]

    def land(out, k, s0):
        for j, v in enumerate(np.asarray(out).ravel()):
            losses[s0 + j] = float(v)

    def land_replay():
        # rung 1 healed the condemned window: futures fetched before the
        # trip were computed on the poisoned timeline — the replay's
        # (materialized, clean-polled) outputs supersede them
        n = 0
        del pending[:]
        for s0, k, out in policy.last_replay:
            land(out[0], k, s0)
            n += k
        return n

    def saved(step_id):
        if ck.maybe_save(0, step_id):
            policy.note_checkpoint(step_id)

    _harness.stage('train')
    with fluid.scope_guard(scope):
        if meta is None:
            exe.run(startup)
            # restore point BEFORE any step: recovery can roll back even
            # a first-step divergence
            ck.save(0, -1)
            ck.wait()
        step = start
        for stacked, k in pf:
            launch = None
            if args.expect_forensics:
                launch = LaunchRecord(main_prog, stacked, k, [loss], step)
            out = policy.run(lambda: exe.run_steps(
                main_prog, feed_list=stacked, steps=k, fetch_list=[loss],
                as_futures=use_async), launch=launch)
            if stall_mark is None:
                # steady state starts AFTER the first fused launch: the
                # cold-start gap (startup program, initial blocking save,
                # the injected prefetch_stall fault) is not what the
                # async-checkpointing stall budget is about
                stall_mark = int(
                    obs.counters().get('executor.stall_count') or 0)
            if out is None:
                # rolled back: steps pending a verdict were computed on
                # the now-condemned window — drop them with the rollback
                dropped = sum(n for _, n, _ in pending)
                del pending[:]
                skipped += k + dropped
                step += k
                # everything after a rollback must reuse the cached
                # executables: restored numpy params have identical
                # specs, so ANY retrace from here on is a regression
                if retrace_mark is None:
                    retrace_mark = int(
                        obs.counters().get('executor.retraces') or 0)
                continue
            if policy.last_replay is not None:
                healed += land_replay()
                saved(step + k - 1)
                step += k
                continue
            if use_async:
                pending.append((out[0], k, step))
                if exe.nan_clean():
                    # verdict window just polled clean: everything
                    # buffered is good — land it and checkpoint
                    flush_pending()
                    saved(step + k - 1)
            else:
                land(out[0], k, step)
                saved(step + k - 1)
            step += k
        if use_async and pending:
            # end of stream with verdicts still on device: force the poll
            # (through recovery, so a late trip rolls back cleanly)
            def drain():
                exe.poll_nan()
                return []
            tail = policy.run(drain)
            if tail is None:
                skipped += sum(n for _, n, _ in pending)
                del pending[:]
            elif policy.last_replay is not None:
                healed += land_replay()
                saved(step - 1)
            else:
                flush_pending()
                saved(step - 1)
        ck.wait()
    _harness.stage('audit')
    c = obs.counters()
    retraces_after_recovery = 0 if retrace_mark is None else \
        int(c.get('executor.retraces') or 0) - retrace_mark
    steady_stalls = 0 if stall_mark is None else \
        int(c.get('executor.stall_count') or 0) - stall_mark

    loss_values = list(losses.values())
    rec = {
        'start': start,
        'steps_done': len(losses),
        'steps_skipped': skipped,
        'steps_healed': healed,
        'losses_finite': bool(np.all(np.isfinite(loss_values))
                              if loss_values else True),
        # shared schema: observability/export.py SCHEMA['resilience']
        'counters': obs.telemetry_snapshot('resilience',
                                           snapshot=c)['counters'],
        'retraces_after_recovery': retraces_after_recovery,
        'steady_state_stalls': steady_stalls,
    }
    if policy.last_report is not None:
        rec['forensics'] = policy.last_report.to_dict()
        rec['quarantine'] = quarantine.state()
    print(json.dumps(rec))

    if not rec['losses_finite']:
        sys.exit('fault_soak: non-finite loss escaped the recovery policy')
    if args.assert_recovery:
        cc = rec['counters']
        if cc['faults.injected'] < 1:
            sys.exit('fault_soak: no faults injected — PT_FAULT matrix '
                     'not armed?')
        if cc['recovery.rollbacks'] < 1:
            sys.exit('fault_soak: no rollbacks — the nan_step fault did '
                     'not exercise recovery')
        if rec['retraces_after_recovery'] > 0:
            sys.exit('fault_soak: %d retrace(s) after rollback — restored '
                     'state no longer matches the compiled executables'
                     % rec['retraces_after_recovery'])
        if rec['steady_state_stalls'] > 0:
            sys.exit('fault_soak: %d steady-state pipeline stall(s) — '
                     'async checkpointing (or recovery) is blocking the '
                     'step loop' % rec['steady_state_stalls'])
    if args.expect_async:
        cc = rec['counters']
        if exe.nan_poll <= 1:
            sys.exit('fault_soak: --expect-async but nan_poll=%d — set '
                     'PT_ASYNC=1 or PT_NAN_POLL>1' % exe.nan_poll)
        if cc['nan_poll.polls'] < 1:
            sys.exit('fault_soak: --expect-async but the deferred verdict '
                     'was never polled')
        if cc['nan_poll.trips'] < 1:
            sys.exit('fault_soak: --expect-async but no deferred trip — '
                     'the nan_step fault did not exercise the window')
    if args.expect_forensics:
        spec = faults.spec('nan_step')
        if spec is None or spec.at is None or spec.row is None:
            sys.exit('fault_soak: --expect-forensics needs '
                     'PT_FAULT=nan_step:at=N:row=R armed')
        rep = policy.last_report
        if rep is None or not rep.tripped:
            sys.exit('fault_soak: --expect-forensics but no forensic '
                     'verdict (report=%r)' % rep)
        if rep.step != spec.at:
            sys.exit('fault_soak: forensics named step %r, injected at %d'
                     % (rep.step, spec.at))
        if not rep.rows or spec.row not in rep.rows:
            sys.exit('fault_soak: forensics named rows %r, injected row %d'
                     % (rep.rows, spec.row))
        want_op = first_consumer_of(main_prog, 'x')
        if rep.op_type not in (want_op, 'fused:%s' % want_op):
            sys.exit('fault_soak: forensics named op %r, expected %r '
                     '(first consumer of the poisoned feed)'
                     % (rep.op_type, want_op))
        if not rep.source_loc:
            sys.exit('fault_soak: forensic report has no source_loc')
        want_idx = spec.at * BATCH + spec.row
        if want_idx not in quarantine.state():
            sys.exit('fault_soak: sample %d not quarantined (state=%r)'
                     % (want_idx, quarantine.state()))
        if rec['counters']['recovery.escalation.quarantine'] < 1:
            sys.exit('fault_soak: the quarantine rung never healed a '
                     'window (escalation counters=%r)' % rec['counters'])
        ref = reference_losses(fluid, quarantine.state(), args.steps, K)
        common = sorted(set(losses) & set(ref))
        if not any(s > spec.at for s in common):
            sys.exit('fault_soak: no surviving post-injection steps to '
                     'compare (common=%r)' % common)
        mismatch = [s for s in common if losses[s] != ref[s]]
        if mismatch:
            sys.exit('fault_soak: healed run diverges bitwise from the '
                     'uninjected reference at steps %r' % mismatch)
        print(json.dumps({'forensics_parity_steps': common,
                          'forensics_healed_steps': healed}))
    return 0


if __name__ == '__main__':
    _harness.set_tool('FAULT_SOAK')
    _harness.main_guard(main, watchdog_env='PT_SOAK_WATCHDOG_S',
                        flight_tag='fault_soak.watchdog')
