"""One-shot measurement harnesses behind PERF.md's numbers, plus the
reference model-matrix benchmark.  The perf lab fronts both:

    python tools/perflab.py probe decompose   # step-time split by surgery
    python tools/perflab.py probe longctx     # llama long-context steps
    python tools/perflab.py probe attn        # pallas-vs-composed attn grad
    python tools/perflab.py probe soak        # 500-step stability
    python tools/perflab.py probe hlo         # per-HLO xplane ledger
    python tools/perflab.py probe convprobe   # conv fwd/dx/dw microbench
    python tools/perflab.py probe allreduce   # psum/all-gather BW, mesh
    python tools/perflab.py models --model resnet --batch_size 64

(tools/measure.py and tools/fluid_benchmark.py forward here for the old
invocations.)  Probes run on a live chip; every harness prints its
table and exits.  These are the scripts that produced the round-4
PERF.md sections — kept runnable so future rounds re-measure instead of
trusting stale numbers.  Ledgered, gated numbers come from the perflab
scenario matrix instead (docs/perflab.md).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PROBES = ('decompose', 'longctx', 'attn', 'soak', 'hlo', 'convprobe',
          'allreduce')


def _sync(x):
    return np.asarray(x)


def _timed_loop(exe, main, feed, loss, steps=30):
    import jax
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for _ in range(3):
        o, = exe.run(main, feed=feed, fetch_list=[loss])
    _sync(o)
    t0 = time.perf_counter()
    for _ in range(steps):
        o, = exe.run(main, feed=feed, fetch_list=[loss],
                     return_numpy=False)
    _sync(o)
    return (time.perf_counter() - t0) / steps * 1e3


def decompose():
    """Forward / backward / optimizer / CE split (PERF.md
    'Step-time decomposition')."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.models import transformer as tr
    B, T, V = 32, 256, 32000
    feeds = tr.synthetic_batch(np.random.RandomState(0), B, T)

    def run(tag, build):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build()
        main.set_amp(True)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            ms = _timed_loop(exe, main, feeds, loss)
        print('%-28s %7.2f ms' % (tag, ms), flush=True)
        return ms

    def tf(**kw):
        out = tr.transformer(V, V, max_len=T, n_layer=6, n_head=8,
                             d_model=512, d_inner=2048, dropout=0.0,
                             use_flash=True, **kw)
        return out

    run('fwd only', lambda: tf(is_train=False)['loss'])

    def with_opt(opt):
        def build():
            out = tf()
            opt().minimize(out['loss'])
            return out['loss']
        return build
    run('fwd+bwd+SGD', with_opt(lambda: fluid.optimizer.SGD(1e-4)))
    run('fwd+bwd+Adam', with_opt(lambda: fluid.optimizer.Adam(1e-4)))

    def no_ce():
        out = tf()
        loss = layers.reduce_mean(out['logits'])
        fluid.optimizer.Adam(1e-4).minimize(loss)
        return loss
    run('fwd+bwd+Adam, no CE', no_ce)


def longctx():
    """llama long-context train steps (PERF.md 'Long-context llama')."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import llama
    cfg = dict(vocab=32000, d_model=1024, n_layer=8, n_head=16,
               n_kv_head=4, d_ffn=2816, theta=500000.0, max_len=4096)
    for T, B in ((4096, 2), (8192, 1)):
        c = dict(cfg, max_len=T)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = llama.build(c, lr=1e-4)
        main.set_amp(True)
        exe = fluid.Executor()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        feed = llama.make_batch(
            [rng.randint(3, 32000, (T + 1,)) for _ in range(B)], T)
        with fluid.scope_guard(scope):
            exe.run(startup)
            ms = _timed_loop(exe, main, feed, out['loss'], steps=10)
        print('llama T=%5d B=%d: %8.0f tok/s (%.1f ms/step)'
              % (T, B, B * T / ms * 1e3, ms), flush=True)


def attn():
    """pallas vs composed attention fwd+grad (PERF.md crossover table)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import attention as att
    rng = np.random.RandomState(0)

    def bench_grad(fn, args, iters=10):
        g = jax.jit(jax.grad(
            lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))
        out = g(*args)
        _sync(out[0][0, 0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(*args)
        _sync(out[0][0, 0, 0, 0])
        return (time.perf_counter() - t0) / iters * 1e3

    for T in (2048, 4096, 8192):
        q, k, v = (jnp.asarray(rng.randn(2, 8, T, 64), jnp.bfloat16)
                   for _ in range(3))
        att._FWD_PALLAS_MIN_T = 0
        att._BWD_PALLAS_SCORE_BYTES = 0
        tp = bench_grad(
            lambda q, k, v: att.flash_attention(q, k, v, causal=True),
            (q, k, v))
        att._FWD_PALLAS_MIN_T = 1 << 30
        tc = bench_grad(
            lambda q, k, v: att.flash_attention(q, k, v, causal=True),
            (q, k, v))
        print('T=%5d: pallas %7.2f ms   composed %7.2f ms' % (T, tp, tc),
              flush=True)


def soak():
    """500-step stability/convergence (PERF.md 'Sustained-training')."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tr
    B, T, V = 32, 128, 8000
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=V, trg_vocab=V, max_len=T, n_layer=4,
                           n_head=8, d_model=256, d_inner=1024,
                           dropout=0.1, lr=1.0, warmup_steps=400,
                           use_flash=True)
    main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)

    def copy_batch():
        rows = []
        for _ in range(B):
            n = rng.randint(T // 2, T - 1)
            s = rng.randint(3, V, (n,))
            rows.append((np.concatenate([s, [1]]),
                         np.concatenate([[0], s]),
                         np.concatenate([s, [1]])))
        return tr.make_batch(rows, T)

    pool = [{k: jax.device_put(v) for k, v in copy_batch().items()}
            for _ in range(50)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        t0 = time.perf_counter()
        for step in range(500):
            lv, = exe.run(main, feed=pool[step % 50],
                          fetch_list=[out['loss']], return_numpy=False)
            if (step + 1) % 100 == 0:
                print('step %d loss %.3f (%.1fs/100)' %
                      (step + 1, float(_sync(lv).ravel()[0]),
                       time.perf_counter() - t0), flush=True)
                t0 = time.perf_counter()


def _hlo_category_map(hlo_text):
    """Parse optimized HLO text into {instruction_name: category}.
    Fusions are categorized by what their fused computation BODY
    contains (a '%fusion.740' profiler event name says nothing about
    whether it is a GEMM or elementwise glue)."""
    import re
    # '%name = <type> opcode(operands...' — the type can nest parens
    # (tile/memory-space annotations like T(8,128) or S(1)), but the
    # opcode is always the FIRST lowercase word directly followed by '('
    inst_re = re.compile(r'^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*.*?'
                         r'[\s)]([a-z][\w\-]*)\(')
    # computation bodies: '%name (params) -> type {' ... instructions
    comp_has = {}
    cur, ops = None, set()
    for line in hlo_text.splitlines():
        m = re.match(r'(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?\s*'
                     r'(?:->.*)?\{\s*$', line)
        if m and not line.lstrip().startswith('%param'):
            if cur is not None:
                comp_has[cur] = ops
            cur, ops = m.group(1), set()
            continue
        m = inst_re.match(line)
        if m:
            ops.add(m.group(2))
    if cur is not None:
        comp_has[cur] = ops

    def body_cat(body_ops):
        if 'dot' in body_ops:
            return 'matmul'
        if 'convolution' in body_ops:
            return 'conv'
        if 'scatter' in body_ops:
            return 'scatter'
        if 'gather' in body_ops or 'dynamic-slice' in body_ops:
            return 'gather/slice'
        if 'custom-call' in body_ops:
            return 'custom-call (pallas)'
        if 'reduce' in body_ops:
            return 'reduce+elementwise'
        return 'elementwise'

    cat = {}
    for line in hlo_text.splitlines():
        m = inst_re.match(line)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        if opcode == 'fusion':
            mc = re.search(r'calls=%?([\w.\-]+)', line)
            body = comp_has.get(mc.group(1), set()) if mc else set()
            cat[name] = body_cat(body)
        elif opcode == 'dot':
            cat[name] = 'matmul'
        elif opcode == 'convolution':
            cat[name] = 'conv'
        elif opcode in ('copy', 'transpose', 'bitcast',
                        'copy-start', 'copy-done'):
            cat[name] = 'copy/transpose'
        elif opcode == 'custom-call':
            cat[name] = 'custom-call (pallas)'
        elif opcode in ('all-reduce', 'all-gather', 'reduce-scatter',
                        'collective-permute'):
            cat[name] = 'collective'
        else:
            cat[name] = opcode
    return cat


def hlo(steps=10, top=30):
    """Per-HLO ledger of the bench train step (PERF.md 'Where the MFU
    ceiling actually is'): trace `steps` steps with jax.profiler, parse
    the xplane with jax.profiler.ProfileData, aggregate the TensorCore
    'XLA Ops' line (serialized sync ops — sums to the step wall) by
    category via the after-optimizations HLO dump, and print the top
    entries.  Async DMA ('Async XLA Ops') overlaps the sync timeline and
    is reported separately, not summed in.  This is HLO granularity —
    the evidence level the round-4 verdict asked for behind any 'the
    gap is diffuse' claim.  PT_HLO_MODEL=resnet profiles the ResNet-50
    bench step instead; PT_HLO_FILTER=<category> lists one category."""
    import glob
    import tempfile
    import jax
    import paddle_tpu as fluid
    if os.environ.get('PT_HLO_MODEL') == 'resnet':
        from paddle_tpu.models import resnet
        main, startup, out, feed = resnet.bench_program()
    else:
        from paddle_tpu.models import transformer as tr
        B, T, V = 32, 256, 32000
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = tr.build(src_vocab=V, trg_vocab=V, max_len=T,
                               n_layer=6, n_head=8, d_model=512,
                               d_inner=2048, dropout=0.0, use_flash=True)
        feed = tr.synthetic_batch(np.random.RandomState(0), B, T)
        main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(3):
            lv, = exe.run(main, feed=feed, fetch_list=[out['loss']])
        _sync(lv)
        tmpdir = tempfile.mkdtemp(prefix='hlo_trace_')
        with jax.profiler.trace(tmpdir):
            for _ in range(steps):
                lv, = exe.run(main, feed=feed, fetch_list=[out['loss']],
                              return_numpy=False)
            _sync(lv)
        # optimized HLO for fusion->category mapping: re-lower+compile
        # the SAME jitted step (deterministic naming; the axon tunnel
        # compiles remotely, so --xla_dump_to can't reach the files)
        entry = next(e for k, e in exe._cache.items() if k[0] == id(main))
        fn, params_in = entry[0], entry[1]
        params = {n: scope.vars[n] for n in params_in}
        hlo_text = fn.lower(params, feed, np.uint32(0)).compile().as_text()
        open('/tmp/hlo_step.txt', 'w').write(hlo_text)
    paths = glob.glob(os.path.join(tmpdir, '**', '*.xplane.pb'),
                      recursive=True)
    if not paths:
        print('no xplane.pb written under %s' % tmpdir)
        return
    cat_map = _hlo_category_map(hlo_text)
    pd = jax.profiler.ProfileData.from_file(paths[0])
    per_op, async_ns, step_ns, nsteps = {}, 0, 0, 0
    for plane in pd.planes:
        if not plane.name.startswith('/device:TPU'):
            continue
        for line in plane.lines:
            if line.name == 'XLA Ops':
                for ev in line.events:
                    per_op[ev.name] = per_op.get(ev.name, 0) + ev.duration_ns
            elif line.name == 'Async XLA Ops':
                async_ns += sum(ev.duration_ns for ev in line.events)
            elif line.name == 'Steps':
                for ev in line.events:
                    step_ns += ev.duration_ns
                    nsteps += 1
    if not per_op:
        print('no sync XLA Ops events found')
        return

    def _cat(event_name):
        iname = event_name.split(' = ')[0].strip().lstrip('%')
        return cat_map.get(iname, 'unmapped')

    total = sum(per_op.values())
    print('%d distinct sync HLO ops; TensorCore busy %.2f ms/step; '
          'step wall %.2f ms (x%d); async DMA span %.2f ms/step (overlapped)'
          % (len(per_op), total / 1e6 / steps,
             step_ns / 1e6 / max(nsteps, 1), nsteps, async_ns / 1e6 / steps))
    cats = {}
    for name, ns in per_op.items():
        c = _cat(name)
        cats[c] = cats.get(c, 0) + ns
    print('\n-- category totals (sync TensorCore time) --')
    for c, ns in sorted(cats.items(), key=lambda kv: -kv[1]):
        print('%-28s %8.3f ms/step  %5.1f%%'
              % (c, ns / 1e6 / steps, 100.0 * ns / total))
    only = os.environ.get('PT_HLO_FILTER')  # show one category's ops
    print('\n-- top %d sync HLO ops%s --'
          % (top, ' [%s]' % only if only else ''))
    shown = 0
    for name, ns in sorted(per_op.items(), key=lambda kv: -kv[1]):
        if only and _cat(name) != only:
            continue
        print('%7.3f ms/step %5.1f%%  [%s]  %s'
              % (ns / 1e6 / steps, 100.0 * ns / total, _cat(name),
                 name[:100]))
        shown += 1
        if shown >= top:
            break


def convprobe():
    """Forward / input-grad / filter-grad conv microbench at
    representative ResNet-50 shapes (round-4 only probed the forward;
    the 0.148-vs-0.20 MFU gap question is whether backward convs run
    slower than the ~20%-of-peak forward ceiling).  bf16, B=128,
    NCHW like the model."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    B = 128
    dn = jax.lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1),
                                        ('NCHW', 'OIHW', 'NCHW'))
    shapes = [  # (Cin, Cout, HW, k, stride) mid/late-net ResNet shapes
        (64, 64, 56, 3, 1),
        (128, 128, 28, 3, 1),
        (256, 256, 14, 3, 1),
        (512, 512, 7, 3, 1),
        (64, 256, 56, 1, 1),
        (256, 128, 56, 1, 2),
    ]
    print('conv probe (bf16, B=%d, NCHW); TFLOP/s vs 197 peak' % B)
    for cin, cout, hw, k, s in shapes:
        x = jnp.asarray(rng.randn(B, cin, hw, hw), jnp.bfloat16)
        w = jnp.asarray(rng.randn(cout, cin, k, k), jnp.bfloat16)
        pad = 'SAME' if k > 1 else 'VALID'

        def conv(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (s, s), pad, dimension_numbers=dn)

        out_hw = hw // s
        flops = 2.0 * B * cout * cin * k * k * out_hw * out_hw

        def timed(f, lead, *args):
            """Differential in-jit timing.  Three tunnel/compiler traps,
            each hit while building this (PERF.md r5): (1) a synchronous
            dispatch through the axon tunnel costs ~60 ms regardless of
            work, so the op runs N times inside ONE jitted fori_loop at
            two N values and the delta/(N2-N1) cancels the constant;
            (2) the loop body must consume a FULL reduction of the
            output — consuming one element let XLA slice the probed
            conv down to computing a single output pixel; (3) the
            iteration-decorrelating perturbation must use a NORMAL f32
            constant — 1e-45 is a denormal, which TPU flushes to zero
            and XLA folds away, hoisting the op out of the loop."""

            def many_fn(n):
                @jax.jit
                def many(lead, args):
                    def body(_, acc):
                        pj = (lead.astype(jnp.float32) *
                              (1.0 + acc * 1e-10)).astype(lead.dtype)
                        o = f(pj, *args)
                        return acc + jnp.sum(o.astype(jnp.float32)) * 1e-20
                    return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
                return many

            def once(m):
                t0 = time.perf_counter()
                _sync(m(lead, args))
                return time.perf_counter() - t0

            times = {}
            for n in (10, 110):
                m = many_fn(n)
                _sync(m(lead, args))  # compile
                times[n] = min(once(m) for _ in range(3))
            return (times[110] - times[10]) / 100.0

        tf_ = timed(lambda x, w: conv(x, w), x, w)
        _, vjp_x = jax.vjp(lambda x: conv(x, w), x)
        ct = jnp.ones((B, cout, out_hw, out_hw), jnp.bfloat16)
        gx = timed(lambda c: vjp_x(c)[0], ct)
        _, vjp_w = jax.vjp(lambda w: conv(x, w), w)
        gw = timed(lambda c: vjp_w(c)[0], ct)
        print('C%4d->%4d %3dx%-3d k%d s%d | fwd %6.2fms %5.1fTF | '
              'dx %6.2fms %5.1fTF | dw %6.2fms %5.1fTF'
              % (cin, cout, hw, hw, k, s,
                 tf_ * 1e3, flops / tf_ / 1e12,
                 gx * 1e3, flops / gx / 1e12,
                 gw * 1e3, flops / gw / 1e12), flush=True)


def allreduce():
    """Collective bandwidth over the local mesh (BASELINE.json headline
    metric #3; the path the reference serves with NCCL —
    nccl_helper.h).  Measures psum (allreduce), all-gather and
    reduce-scatter bus bandwidth; prints null single-chip (one chip has
    no ICI to measure) so the harness degrades gracefully."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()
    if len(devs) < 2:
        print(json.dumps({'devices': len(devs), 'allreduce_gbps': None,
                          'all_gather_gbps': None,
                          'reduce_scatter_gbps': None,
                          'note': 'single device: no interconnect to '
                                  'measure; run on a mesh'}))
        return
    mesh = Mesh(np.array(devs), ('x',))
    nd = len(devs)
    results = {'devices': nd}
    for nbytes in (1 << 20, 16 << 20, 64 << 20):
        n = nbytes // 4 // nd * nd
        x = jnp.ones((n,), jnp.float32)

        def run(body, out_specs):
            f = jax.jit(shard_map(body, mesh=mesh, in_specs=P('x'),
                                  out_specs=out_specs))
            f(x).block_until_ready()
            t0 = time.perf_counter()
            iters = 10
            for _ in range(iters):
                o = f(x)
            o.block_until_ready()
            return (time.perf_counter() - t0) / iters

        # ring-algorithm bus-bandwidth accounting (the convention NCCL
        # tests print): allreduce moves 2(n-1)/n, gather/scatter (n-1)/n
        dt = run(lambda s: jax.lax.psum(s, 'x'), P(None))
        results['allreduce_gbps_%dMB' % (nbytes >> 20)] = round(
            2 * (nd - 1) / nd * n * 4 / dt / 1e9, 2)
        dt = run(lambda s: jax.lax.all_gather(s, 'x', tiled=True), P(None))
        results['all_gather_gbps_%dMB' % (nbytes >> 20)] = round(
            (nd - 1) / nd * n * 4 / dt / 1e9, 2)
        dt = run(lambda s: jax.lax.psum_scatter(s, 'x', tiled=True), P('x'))
        results['reduce_scatter_gbps_%dMB' % (nbytes >> 20)] = round(
            (nd - 1) / nd * n * 4 / dt / 1e9, 2)
    print(json.dumps(results))


# -------------------------------- model matrix (ex fluid_benchmark.py)
# Parity: reference benchmark/fluid/fluid_benchmark.py + args.py — same
# CLI shape (--model/--batch_size/--iterations/--skip_batch_num/
# --learning_rate), same model set, synthetic data, prints per-model
# throughput.  One whole-step XLA executable per model; the timed loop
# runs async with a single sync at the end (steady-state training
# measures the chip, not per-step RTT).
BENCHMARK_MODELS = ['mnist', 'resnet', 'vgg', 'se_resnext',
                    'machine_translation', 'stacked_dynamic_lstm']


def parse_model_args(argv=None):
    p = argparse.ArgumentParser('paddle_tpu model benchmarks.')
    p.add_argument('--model', type=str, choices=BENCHMARK_MODELS,
                   default='resnet')
    p.add_argument('--batch_size', type=int, default=32)
    p.add_argument('--learning_rate', type=float, default=None,
                   help='override each model\'s default lr/schedule scale')
    p.add_argument('--skip_batch_num', type=int, default=5,
                   help='warmup minibatches excluded from timing')
    p.add_argument('--iterations', type=int, default=30,
                   help='timed minibatches')
    p.add_argument('--seq_len', type=int, default=256,
                   help='sequence length (translation / lstm models)')
    p.add_argument('--class_dim', type=int, default=1000)
    p.add_argument('--image_size', type=int, default=224)
    p.add_argument('--device', type=str, default='TPU',
                   choices=['CPU', 'TPU'],
                   help='CPU forces the host backend')
    return p.parse_args(argv)


def _build_model(args):
    import paddle_tpu as fluid
    rng = np.random.RandomState(0)
    B = args.batch_size

    def lr_kw(default):
        return {'lr': args.learning_rate
                if args.learning_rate is not None else default}

    if args.model == 'mnist':
        from paddle_tpu.models import mnist as m
        out = m.build(**lr_kw(0.001))
        feed = {'pixel': rng.rand(B, 1, 28, 28).astype('float32'),
                'label': rng.randint(0, 10, (B, 1)).astype('int64')}
        unit, per_step = 'images/s', B
    elif args.model in ('resnet', 'vgg', 'se_resnext'):
        shape = (3, args.image_size, args.image_size)
        if args.model == 'resnet':
            from paddle_tpu.models import resnet as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          depth=50, **lr_kw(0.1))
        elif args.model == 'vgg':
            from paddle_tpu.models import vgg as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          **lr_kw(1e-3))
        else:
            from paddle_tpu.models import se_resnext as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          **lr_kw(0.1))
        feed = {'data': rng.rand(B, *shape).astype('float32'),
                'label': rng.randint(0, args.class_dim,
                                     (B, 1)).astype('int64')}
        unit, per_step = 'images/s', B
    elif args.model == 'machine_translation':
        from paddle_tpu.models import transformer as tr
        T = args.seq_len
        out = tr.build(src_vocab=32000, trg_vocab=32000, max_len=T,
                       n_layer=6, n_head=8, d_model=512, d_inner=2048,
                       dropout=0.0, use_flash=True,
                       **lr_kw(2.0))   # lr scales the noam schedule here
        feed = tr.synthetic_batch(rng, B, T)
        unit, per_step = 'tokens/s', B * T
    else:  # stacked_dynamic_lstm
        from paddle_tpu.models import stacked_lstm as m
        from paddle_tpu.core.lod import create_lod_tensor
        out = m.build(**lr_kw(0.002))
        T = min(args.seq_len, 128)
        rows = [rng.randint(2, 5147, (T, 1)).astype('int64')
                for _ in range(B)]
        feed = {'words': create_lod_tensor(rows),
                'label': rng.randint(0, 2, (B, 1)).astype('int64')}
        unit, per_step = 'words/s', B * T
    return out, feed, unit, per_step


def models_main(argv=None):
    args = parse_model_args(argv)
    if args.device == 'CPU':
        import jax
        jax.config.update('jax_platforms', 'cpu')
    import jax
    import paddle_tpu as fluid

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out, feed, unit, per_step = _build_model(args)
    if args.device != 'CPU':
        main_prog.set_amp(True)

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {k: (v if hasattr(v, 'padded') else jax.device_put(v))
                for k, v in feed.items()}
        t0 = time.perf_counter()
        for _ in range(max(1, args.skip_batch_num)):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']])
        np.asarray(loss)
        print('%s: compile+warmup %.1fs'
              % (args.model, time.perf_counter() - t0), file=sys.stderr)
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']],
                            return_numpy=False)
        final = float(np.asarray(loss).reshape(()))
        dt = time.perf_counter() - t0
    tput = args.iterations * per_step / dt
    print(json.dumps({
        'model': args.model, 'batch_size': args.batch_size,
        'iterations': args.iterations, 'throughput': round(tput, 1),
        'unit': unit, 'final_loss': round(final, 4),
        'backend': jax.devices()[0].device_kind,
    }))
    return 0


def probe_main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    harness = argv[0] if argv else 'decompose'
    if harness not in PROBES:
        print('unknown probe %r (known: %s)' % (harness,
                                                ', '.join(PROBES)),
              file=sys.stderr)
        return 2
    {'decompose': decompose, 'longctx': longctx,
     'attn': attn, 'soak': soak, 'hlo': hlo,
     'convprobe': convprobe, 'allreduce': allreduce}[harness]()
    return 0


if __name__ == '__main__':
    sys.exit(probe_main())
