"""Thin alias: the reference model-matrix benchmark moved into the perf
lab (`python tools/perflab.py models ...`; implementation in
tools/_probes.py).  This shim keeps the old invocation working:

    python tools/fluid_benchmark.py --model resnet --batch_size 64
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _probes  # noqa: E402

if __name__ == '__main__':
    sys.exit(_probes.models_main())
