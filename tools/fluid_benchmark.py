"""Model benchmark harness.

Parity: reference benchmark/fluid/fluid_benchmark.py + args.py — same
CLI shape (--model/--batch_size/--iterations/--skip_batch_num/
--learning_rate), same model set (mnist, resnet, vgg, se_resnext,
machine_translation, stacked_dynamic_lstm), synthetic data, prints
per-model throughput.  One whole-step XLA executable per model; the
timed loop runs async with a single sync at the end (steady-state
training measures the chip, not per-step RTT).

    python tools/fluid_benchmark.py --model resnet --batch_size 64
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BENCHMARK_MODELS = ['mnist', 'resnet', 'vgg', 'se_resnext',
                    'machine_translation', 'stacked_dynamic_lstm']


def parse_args():
    p = argparse.ArgumentParser('paddle_tpu model benchmarks.')
    p.add_argument('--model', type=str, choices=BENCHMARK_MODELS,
                   default='resnet')
    p.add_argument('--batch_size', type=int, default=32)
    p.add_argument('--learning_rate', type=float, default=None,
                   help='override each model\'s default lr/schedule scale')
    p.add_argument('--skip_batch_num', type=int, default=5,
                   help='warmup minibatches excluded from timing')
    p.add_argument('--iterations', type=int, default=30,
                   help='timed minibatches')
    p.add_argument('--seq_len', type=int, default=256,
                   help='sequence length (translation / lstm models)')
    p.add_argument('--class_dim', type=int, default=1000)
    p.add_argument('--image_size', type=int, default=224)
    p.add_argument('--device', type=str, default='TPU',
                   choices=['CPU', 'TPU'],
                   help='CPU forces the host backend')
    return p.parse_args()


def _build(args):
    import paddle_tpu as fluid
    rng = np.random.RandomState(0)
    B = args.batch_size

    def lr_kw(default):
        return {'lr': args.learning_rate
                if args.learning_rate is not None else default}

    if args.model == 'mnist':
        from paddle_tpu.models import mnist as m
        out = m.build(**lr_kw(0.001))
        feed = {'pixel': rng.rand(B, 1, 28, 28).astype('float32'),
                'label': rng.randint(0, 10, (B, 1)).astype('int64')}
        unit, per_step = 'images/s', B
    elif args.model in ('resnet', 'vgg', 'se_resnext'):
        shape = (3, args.image_size, args.image_size)
        if args.model == 'resnet':
            from paddle_tpu.models import resnet as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          depth=50, **lr_kw(0.1))
        elif args.model == 'vgg':
            from paddle_tpu.models import vgg as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          **lr_kw(1e-3))
        else:
            from paddle_tpu.models import se_resnext as m
            out = m.build(data_shape=shape, class_dim=args.class_dim,
                          **lr_kw(0.1))
        feed = {'data': rng.rand(B, *shape).astype('float32'),
                'label': rng.randint(0, args.class_dim,
                                     (B, 1)).astype('int64')}
        unit, per_step = 'images/s', B
    elif args.model == 'machine_translation':
        from paddle_tpu.models import transformer as tr
        T = args.seq_len
        out = tr.build(src_vocab=32000, trg_vocab=32000, max_len=T,
                       n_layer=6, n_head=8, d_model=512, d_inner=2048,
                       dropout=0.0, use_flash=True,
                       **lr_kw(2.0))   # lr scales the noam schedule here
        feed = tr.synthetic_batch(rng, B, T)
        unit, per_step = 'tokens/s', B * T
    else:  # stacked_dynamic_lstm
        from paddle_tpu.models import stacked_lstm as m
        from paddle_tpu.core.lod import create_lod_tensor
        out = m.build(**lr_kw(0.002))
        T = min(args.seq_len, 128)
        rows = [rng.randint(2, 5147, (T, 1)).astype('int64')
                for _ in range(B)]
        feed = {'words': create_lod_tensor(rows),
                'label': rng.randint(0, 2, (B, 1)).astype('int64')}
        unit, per_step = 'words/s', B * T
    return out, feed, unit, per_step


def main():
    args = parse_args()
    if args.device == 'CPU':
        import jax
        jax.config.update('jax_platforms', 'cpu')
    import jax
    import paddle_tpu as fluid

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out, feed, unit, per_step = _build(args)
    if args.device != 'CPU':
        main_prog.set_amp(True)

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {k: (v if hasattr(v, 'padded') else jax.device_put(v))
                for k, v in feed.items()}
        t0 = time.perf_counter()
        for _ in range(max(1, args.skip_batch_num)):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']])
        np.asarray(loss)
        print('%s: compile+warmup %.1fs'
              % (args.model, time.perf_counter() - t0), file=sys.stderr)
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']],
                            return_numpy=False)
        final = float(np.asarray(loss).reshape(()))
        dt = time.perf_counter() - t0
    tput = args.iterations * per_step / dt
    print(json.dumps({
        'model': args.model, 'batch_size': args.batch_size,
        'iterations': args.iterations, 'throughput': round(tput, 1),
        'unit': unit, 'final_loss': round(final, 4),
        'backend': jax.devices()[0].device_kind,
    }))


if __name__ == '__main__':
    sys.exit(main())
