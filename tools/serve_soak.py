#!/usr/bin/env python
"""Serving soak: a closed+open-loop load generator that drives the
ServingEngine under the armed PT_FAULT matrix and asserts the SLOs.

Traffic:
  * CLOSED loop — ``--clients`` threads, each submits one request with a
    generous deadline, waits for the terminal reply, repeats.  Models
    well-behaved callers and guarantees a stream of successes for the
    latency percentiles.
  * OPEN loop — the main thread fires ``--requests`` requests at
    ``--qps`` regardless of replies, each with a ``--deadline-ms``
    deadline.  Models the traffic that does NOT slow down when the
    server does — the load that admission control and shedding exist
    for.

Chaos (armed by the caller via PT_FAULT, see docs/serving.md):
  ``serve_slow_batch`` latency spikes, ``serve_dispatch`` batch failures
  (trips the breaker; it must also RECOVER), ``queue_overflow`` forced
  sheds, ``compile_storm`` cold-compile storms, and ``sigterm`` — the
  soak delivers a real SIGTERM to itself at open-loop request index
  ``at`` and the engine must drain: finish in-flight work, refuse new
  requests, reach STOPPED, with the process alive to report.

Asserted SLOs (--assert-slo), all from ``serving.*`` metrics:
  * every admitted request got a terminal reply; ``serving.deadlocks``
    == 0; counters reconcile (admitted == completed + errors +
    deadline_exceeded + shed)
  * p99 latency is finite (and there WERE successes)
  * shed rate <= --shed-ceiling
  * breaker tripped AND recovered (--expect-breaker)
  * SIGTERM drain observed: handler ran, engine STOPPED, post-drain
    submissions refused (--expect-drain)

Observability gates (docs/observability.md):
  * --trace-out PATH exports the Perfetto trace and VERIFIES it: a
    chosen successful request has exactly ONE `serving.request` root
    span, that root links (via its children's batch_span_id) to a
    `serving.batch` span whose `links` carry the request's trace id,
    and the queue_wait + dispatch + device child spans cover >= 90% of
    the root span's duration — the trace actually answers "why was
    this request slow".
  * --metrics-port N starts the engine-owned /metrics endpoint; the
    soak scrapes it mid-run (serving_admitted_total present) and again
    post-drain, asserting the scraped accounting identity
    admitted == completed + errors + deadline_exceeded + shed.
  * --expect-flight requires a flight-recorder dump in PT_FLIGHT_DIR
    containing at least one `serving.batch` span and a
    `fault.injected` serve_dispatch event (the mid-batch crash left a
    usable postmortem).

``--scenario decode`` switches to the streaming-generation soak
(`run_decode_scenario`): open-loop token-stream load over the PAGED KV
pool with mixed prompt lengths, mid-soak cancellations, overlong-prompt
refusals, the ``decode_step`` fault site, and token-level SLO gates
(TTFT/ITL histograms, bitwise greedy parity over the same page
geometry/quantization, zero post-warmup compiles, no KV slot OR page
leaks, prefix-cache hits when shared prompts flow, live draft/verify
acceptance when --speculative) — see docs/generation.md.
``--capacity-floor N`` appends the fixed-budget density gate
(`run_capacity_gate`): a hard KV byte budget, an oversubscribed slot
table, and a stream ramp that must queue at admission backpressure —
never die mid-stream — while sustaining >= N concurrent streams at SLO
(ledgered as the ``decode_capacity`` scenario).

Prints one JSON line with the verdict and the metrics that prove it
(the serving block comes from observability.telemetry_snapshot, the
same schema bench.py and fault_soak.py print).
"""
import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _harness  # noqa: E402 - shared stage/watchdog/JSON-tail contract


def build_predictor_backend(tmpdir):
    """Tiny real model through the full stack: save_inference_model ->
    Predictor (per-bucket AOT executables, single-flight compiles)."""
    import numpy as np
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            h = fluid.layers.fc(x, 16, act='relu')
            probs = fluid.layers.fc(h, 4, act='softmax')
    exe, scope = fluid.Executor(), fluid.Scope()
    model_dir = os.path.join(tmpdir, 'model')
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ['x'], [probs], exe, main)
    predictor = fluid.inference.Predictor(model_dir)
    return predictor.run


def build_stub_backend(latency_s):
    import numpy as np

    def backend(feed):
        if latency_s:
            time.sleep(latency_s)
        x = np.asarray(feed['x'])
        return [x.sum(axis=tuple(range(1, x.ndim)), keepdims=True)]
    return backend


def run_decode_scenario(args):
    """Streaming-decode soak (--scenario decode): open-loop generation
    load with mixed prompt lengths against a GenerationEngine, mid-soak
    client cancellations, and deliberately-overlong prompts that must be
    refused (never truncated).  Asserts, under the armed PT_FAULT matrix
    (``decode_step`` breaks one fused window mid-soak):

      * zero no-reply streams and ``serving.deadlocks == 0``; admitted
        == completed + errors + deadline_exceeded + shed
      * TTFT and ITL histograms populated (the telemetry quantiles are
        finite)
      * at least one mixed prefill+decode dispatch round
      * bitwise greedy parity: the engine's fused K-token stream equals
        a sequential (K=1) single-request reference
      * ZERO new executable compiles after warmup — batch composition,
        prompt length, and sampling params never retrace
      * every KV slot returned to the free list after drain
    """
    import numpy as np
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.serving.engine import ServingConfig
    from paddle_tpu.serving.generation import (DecodeRuntime,
                                               GenerationConfig,
                                               GenerationEngine)
    from paddle_tpu.serving.generation.decode import random_weights

    _flight.install()
    _harness.stage('decode_setup')
    cfg = dict(vocab=128, d_model=32, n_layer=2, n_head=4, n_kv_head=2,
               d_ffn=64, theta=10000.0, max_len=32)
    w = random_weights(cfg, seed=0)
    rt = DecodeRuntime(w, cfg, slots=args.slots, prefill_chunk=4,
                       page_len=args.page_len, pages=args.pages,
                       kv_quant=args.kv_quant)
    K = args.decode_window
    engine = GenerationEngine(
        rt, config=ServingConfig(max_queue=args.max_queue,
                                 drain_timeout_s=30.0),
        gen_config=GenerationConfig(
            decode_window=K,
            speculative=True if args.speculative else None)).start()

    # parity gate first (its executables land before the warmup
    # snapshot): fused engine stream == sequential K=1 reference over
    # the SAME page geometry and quantization (speculative decode, if
    # on, must also be bitwise-invisible here).  The PT_FAULT matrix is
    # disarmed for this pre-flight — fault fire counts (at=N) index
    # into SOAK traffic rounds, not the parity probe — and re-armed
    # from the environment before traffic starts
    from paddle_tpu.testing import faults as _faults
    _faults.configure('')
    ref_prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    ref_rt = DecodeRuntime(w, cfg, slots=1, prefill_chunk=4,
                           page_len=args.page_len, kv_quant=args.kv_quant)
    ref = ref_rt.generate(ref_prompt, 8, steps_per_window=1)
    got = engine.generate(ref_prompt, max_new=8).result(60)
    if not got.ok or list(got.outputs[0]) != ref:
        sys.exit('serve_soak[decode]: greedy parity broken: engine=%r '
                 'sequential=%r'
                 % (list(got.outputs[0]) if got.ok else got.status, ref))

    rt.warmup(steps=K, speculative=args.speculative)
    _faults.configure()
    compiles0 = obs.counters().get('generation.compiles') or 0

    _harness.stage('decode_traffic')
    streams, cancellers = [], []
    overlong = 0
    period = 1.0 / args.qps if args.qps > 0 else 0.0
    lengths = (2, 5, 9, 14, 20)
    # every well-formed prompt opens with one full page of shared
    # "system prefix" so the prefix cache has something real to hit
    shared = ([(3 + j) % (cfg['vocab'] - 1) + 1
               for j in range(rt.cache.page_len)]
              if rt.prefix is not None else [])
    for i in range(args.requests):
        if i % 11 == 10:
            prompt = list(range(1, 40))        # must be REFUSED, whole
            overlong += 1
        else:
            n = lengths[i % len(lengths)]
            prompt = shared + [(7 * i + j) % (cfg['vocab'] - 1) + 1
                               for j in range(n)]
        s = engine.generate(prompt,
                            max_new=min(8, cfg['max_len'] - min(
                                len(prompt), cfg['max_len'] - 1)),
                            temperature=0.8 if i % 3 else 0.0,
                            top_k=5 if i % 3 else 0, seed=i,
                            timeout_s=args.deadline_ms / 1e3)
        streams.append(s)
        if args.cancel_every and i % args.cancel_every \
                == args.cancel_every - 1:
            def canceller(stream=s):
                try:
                    next(stream.tokens(timeout=20.0))
                except (TimeoutError, StopIteration):
                    pass
                stream.cancel()                # mid-stream, after TTFT
            t = threading.Thread(target=canceller, daemon=True)
            t.start()
            cancellers.append(t)
        if period:
            time.sleep(period)
    for t in cancellers:
        t.join(timeout=30.0)
    engine.stop()

    _harness.stage('decode_audit')
    statuses, no_reply = {}, 0
    for s in streams:
        if not s.done():
            no_reply += 1
            continue
        res = s.result(0)
        key = (res.status if res.status != 'rejected'
               else 'rejected.%s' % res.reason)
        statuses[key] = statuses.get(key, 0) + 1

    tel = obs.telemetry_snapshot('serving')
    c = obs.counters()
    compiles_during = (c.get('generation.compiles') or 0) - compiles0
    if rt.prefix is not None:
        rt.prefix.reset()          # cached pages are holds, not leaks
    pages_leaked = int(rt.pool.in_use())
    rec = {
        'scenario': 'decode',
        'requests_submitted': len(streams),
        'statuses': statuses,
        'no_reply': no_reply,
        'cancels_requested': len(cancellers),
        'overlong_submitted': overlong,
        'compiles_after_warmup': compiles_during,
        'mixed_dispatches': int(c.get('generation.mixed_dispatches') or 0),
        'tokens': int(c.get('generation.tokens') or 0),
        'free_slots': rt.free_slots(),
        'kv_pages_leaked': pages_leaked,
        'prefix_hits': int(c.get('generation.prefix_hits') or 0),
        'spec_proposed': int(c.get('generation.spec_proposed') or 0),
        'spec_accepted': int(c.get('generation.spec_accepted') or 0),
        'kv_backpressure': int(c.get('generation.kv_backpressure') or 0),
        'kv_oom': int(c.get('generation.kv_oom') or 0),
        'kv_pool': rt.pool_snapshot(),
        'state': engine.state,
    }
    rec.update(tel)
    print(json.dumps(rec))
    from paddle_tpu.observability import perflab
    perflab.maybe_ledger(
        'serve_soak',
        {'deadlocks': int(rec['deadlocks']), 'no_reply': no_reply,
         'p99_ms': rec.get('p99_ms'),
         'ttft_p99_ms': rec.get('ttft_p99_ms'),
         'itl_p99_ms': rec.get('itl_p99_ms'),
         'scenario': 'decode', 'admitted': rec.get('admitted')})

    if args.assert_slo:
        if no_reply:
            sys.exit('serve_soak[decode]: %d stream(s) never got a '
                     'terminal reply' % no_reply)
        if rec['deadlocks']:
            sys.exit('serve_soak[decode]: serving.deadlocks=%d'
                     % rec['deadlocks'])
        if rec['terminal_replies'] != rec['admitted']:
            sys.exit('serve_soak[decode]: terminal replies (%d) != '
                     'admitted (%d)' % (rec['terminal_replies'],
                                        rec['admitted']))
        if not statuses.get('ok'):
            sys.exit('serve_soak[decode]: zero successful streams')
        for q in ('ttft_p50_ms', 'ttft_p99_ms', 'itl_p50_ms',
                  'itl_p99_ms'):
            if rec[q] is None or not np.isfinite(rec[q]):
                sys.exit('serve_soak[decode]: %s is not finite: %r — '
                         'token-level SLO histogram unpopulated'
                         % (q, rec[q]))
        if rec['mixed_dispatches'] < 1:
            sys.exit('serve_soak[decode]: no mixed prefill+decode '
                     'dispatch round observed')
        if compiles_during:
            sys.exit('serve_soak[decode]: %d executable compile(s) after '
                     'warmup — decode loop retraced' % compiles_during)
        if overlong and not statuses.get('rejected.too_long'):
            sys.exit('serve_soak[decode]: overlong prompts were not '
                     'refused as too_long')
        if len(streams) > len(cancellers) + overlong \
                and not statuses.get('shed'):
            sys.exit('serve_soak[decode]: cancellations produced no shed '
                     'replies')
        if rec['free_slots'] != rt.slots:
            sys.exit('serve_soak[decode]: %d/%d KV slots leaked'
                     % (rt.slots - rec['free_slots'], rt.slots))
        if pages_leaked:
            sys.exit('serve_soak[decode]: %d KV pages still allocated '
                     'after drain (post prefix-cache reset)'
                     % pages_leaked)
        if rt.prefix is not None and rec['prefix_hits'] < 1:
            sys.exit('serve_soak[decode]: shared-prefix prompts produced '
                     'no prefix-cache hits')
        if args.speculative and (rec['spec_proposed'] < 1
                                 or rec['spec_accepted'] < 1):
            sys.exit('serve_soak[decode]: speculative decode proposed=%d '
                     'accepted=%d — draft/verify pipeline inert'
                     % (rec['spec_proposed'], rec['spec_accepted']))
        if rec['state'] != 'stopped':
            sys.exit('serve_soak[decode]: engine did not reach STOPPED '
                     '(state=%s)' % rec['state'])
    if args.capacity_floor:
        return run_capacity_gate(args, w, cfg)
    return 0


def run_capacity_gate(args, w, cfg):
    """Fixed-budget serving-density gate (--capacity-floor N): size the
    page pool to a hard byte budget, oversubscribe the slot table, and
    ram the engine with more streams than the pages can hold at once.
    The excess must queue at ADMISSION (generation.kv_backpressure > 0)
    — never die mid-stream with kv_oom — every stream must still finish
    OK, and the peak concurrency the budget sustained must beat the
    floor.  With int8 pages the floor is set at >= 4x the streams a
    dense PR-11 layout (one f32 max_len strip each) could reserve in
    the same bytes.  The verdict is ledgered as ``decode_capacity``.

    ``max_new = decode_window + 1`` keeps every stream inside its
    admission-time page span (one prefill token plus exactly one fused
    window), so admission is provably the only pressure path."""
    import numpy as np  # noqa: F401 - parity with sibling scenarios
    import paddle_tpu.observability as obs
    from paddle_tpu.serving.engine import ServingConfig
    from paddle_tpu.serving.generation import (CacheConfig, DecodeRuntime,
                                               GenerationConfig,
                                               GenerationEngine)
    from paddle_tpu.testing import faults as _faults

    _harness.stage('decode_capacity')
    _faults.configure('')   # density measurement, not chaos: run clean
    K = args.decode_window
    quant = args.kv_quant or 'int8'
    page_len = args.page_len or 4
    geom = CacheConfig(slots=1, layers=cfg['n_layer'],
                       kv_heads=cfg['n_kv_head'], max_len=cfg['max_len'],
                       head_dim=cfg['d_model'] // cfg['n_head'],
                       page_len=page_len, quant=quant)
    budget = args.capacity_budget
    pages = max(2, budget // geom.page_bytes() + 1)   # +1: garbage page
    dense_streams = max(1, budget // geom.dense_slot_bytes())
    # oversubscribed slot table: pages, not slots, must bind admission;
    # prefix cache off so every stream has identical page demand
    slots = 16
    rt = DecodeRuntime(w, cfg, slots=slots, prefill_chunk=4,
                       page_len=page_len, pages=pages, kv_quant=quant,
                       prefix_cache=False)
    engine = GenerationEngine(
        rt, config=ServingConfig(max_queue=256, drain_timeout_s=60.0),
        gen_config=GenerationConfig(decode_window=K,
                                    speculative=False)).start()
    rt.warmup(steps=K)
    bp0 = int(obs.counters().get('generation.kv_backpressure') or 0)

    peak = [0]
    done = threading.Event()

    def poll():
        while not done.is_set():
            peak[0] = max(peak[0], rt.allocator.in_use())
            time.sleep(0.001)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    requests = 3 * slots
    streams = []
    for i in range(requests):
        n = 1 + (i % 3)
        prompt = ([(3 + j) % (cfg['vocab'] - 1) + 1
                   for j in range(page_len)] +
                  [(7 * i + j) % (cfg['vocab'] - 1) + 1 for j in range(n)])
        streams.append(engine.generate(prompt, max_new=K + 1, seed=i,
                                       timeout_s=120.0))
    ok = 0
    for s in streams:
        try:
            ok += 1 if s.result(120).ok else 0
        except Exception:
            pass
    done.set()
    poller.join(1.0)
    engine.stop()

    backpressure = (int(obs.counters().get('generation.kv_backpressure')
                        or 0) - bp0)
    pages_leaked = int(rt.pool.in_use())
    slo_held = (ok == requests and pages_leaked == 0
                and rt.free_slots() == rt.slots)
    streams_at_slo = int(peak[0]) if slo_held else 0
    floor = args.capacity_floor
    rec = {'scenario': 'decode_capacity', 'requests': requests,
           'streams_ok': ok, 'kv_budget_bytes': budget,
           'page_len': page_len, 'kv_quant': quant, 'pages': pages,
           'dense_streams_in_budget': dense_streams,
           'kv_backpressure': backpressure,
           'kv_pages_leaked': pages_leaked,
           'streams_at_slo': streams_at_slo,
           'density_x_vs_dense': streams_at_slo // dense_streams,
           'capacity_floor': floor}
    print(json.dumps(rec))
    from paddle_tpu.observability import perflab
    perflab.maybe_ledger(
        'decode_capacity',
        {'streams_at_slo': streams_at_slo,
         'kv_pages_leaked': pages_leaked,
         'density_x_vs_dense': rec['density_x_vs_dense'],
         'capacity_floor': floor, 'kv_budget_bytes': budget,
         'page_len': page_len, 'kv_quant': quant})
    if ok != requests:
        sys.exit('serve_soak[capacity]: %d/%d streams failed under the '
                 'page budget — backpressure must queue, never kill'
                 % (requests - ok, requests))
    if backpressure < 1:
        sys.exit('serve_soak[capacity]: the ramp never hit admission '
                 'backpressure — the budget was not binding, density '
                 'unproven')
    if pages_leaked:
        sys.exit('serve_soak[capacity]: %d KV pages still allocated '
                 'after drain' % pages_leaked)
    if streams_at_slo < floor:
        sys.exit('serve_soak[capacity]: %d concurrent streams at SLO '
                 'under a %d-byte budget — floor is %d'
                 % (streams_at_slo, budget, floor))
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--scenario', default='oneshot',
                    choices=('oneshot', 'decode'),
                    help='oneshot: the PR-8 request/reply soak; decode: '
                         'streaming generation over the KV-cache runtime')
    ap.add_argument('--requests', type=int, default=80,
                    help='open-loop request count')
    ap.add_argument('--qps', type=float, default=120.0,
                    help='open-loop submission rate')
    ap.add_argument('--clients', type=int, default=3,
                    help='closed-loop client threads')
    ap.add_argument('--deadline-ms', type=float, default=2000.0,
                    help='open-loop per-request deadline')
    ap.add_argument('--max-queue', type=int, default=32)
    ap.add_argument('--policy', default='shed_oldest',
                    choices=('reject', 'block', 'shed_oldest'))
    ap.add_argument('--shed-ceiling', type=float, default=0.35,
                    help='max tolerated shed fraction of admitted')
    ap.add_argument('--stub', action='store_true',
                    help='stub backend (no compiles) instead of a real '
                         'Predictor')
    ap.add_argument('--stub-latency-ms', type=float, default=2.0)
    ap.add_argument('--assert-slo', action='store_true')
    ap.add_argument('--expect-breaker', action='store_true',
                    help='require breaker tripped AND recovered')
    ap.add_argument('--expect-drain', action='store_true',
                    help='require a SIGTERM-initiated drain was observed')
    ap.add_argument('--trace-out', default=None, metavar='PATH',
                    help='export the Perfetto trace here and verify a '
                         'request decomposes into queue/dispatch/device '
                         'child spans linked to its batch span')
    ap.add_argument('--metrics-port', type=int, default=None,
                    help='engine-owned /metrics port (0 = ephemeral); '
                         'the soak scrapes it mid-run and post-drain')
    ap.add_argument('--expect-flight', action='store_true',
                    help='require a flight dump with a serving.batch '
                         'span and a serve_dispatch fault event')
    ap.add_argument('--slots', type=int, default=4,
                    help='[decode] KV cache slots')
    ap.add_argument('--decode-window', type=int, default=4,
                    help='[decode] tokens per fused decode launch')
    ap.add_argument('--cancel-every', type=int, default=7,
                    help='[decode] cancel every Nth stream after its '
                         'first token (0 = never)')
    ap.add_argument('--kv-quant', default=None, choices=('none', 'int8'),
                    help='[decode] KV page quantization (default: env '
                         'PT_KV_QUANT)')
    ap.add_argument('--page-len', type=int, default=None,
                    help='[decode] tokens per KV page (default: largest '
                         'divisor of max_len that is <= 8)')
    ap.add_argument('--pages', type=int, default=None,
                    help='[decode] KV pool depth (default: enough for '
                         'every slot at max_len)')
    ap.add_argument('--speculative', action='store_true',
                    help='[decode] draft+verify speculative decoding')
    ap.add_argument('--capacity-floor', type=int, default=0,
                    help='[decode] after the soak, run the fixed-budget '
                         'capacity gate and require at least this many '
                         'concurrent streams at SLO (0 = skip)')
    ap.add_argument('--capacity-budget', type=int, default=16384,
                    help='[decode] KV byte budget for the capacity gate')
    args = ap.parse_args()
    if args.scenario == 'decode':
        return run_decode_scenario(args)

    import numpy as np
    import paddle_tpu.observability as obs
    from paddle_tpu import serving
    from paddle_tpu.data_feeder import FeedBucketer
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.testing import faults as _faults

    _flight.install()   # an uncaught crash still leaves a postmortem

    _harness.stage('setup')
    import tempfile
    tmpdir = tempfile.mkdtemp(prefix='pt_serve_soak.')
    backend = (build_stub_backend(args.stub_latency_ms / 1e3) if args.stub
               else build_predictor_backend(tmpdir))

    bucketer = FeedBucketer(boundaries=[1, 2, 4, 8, 16, 32])
    engine = serving.ServingEngine(
        backend, bucketer=bucketer,
        config=serving.ServingConfig(
            max_queue=args.max_queue, overflow_policy=args.policy,
            max_batch_rows=32, batch_linger_s=0.002,
            breaker_failure_threshold=3, breaker_storm_threshold=3,
            breaker_cooldown_s=0.2, drain_timeout_s=20.0,
            metrics_port=args.metrics_port))

    # the soak's own SIGTERM recorder goes in FIRST so the engine's
    # drain handler (installed second) chains to it — the process stays
    # alive to finish the drain and report, proving handler composition
    sigterm_seen = [False]
    signal.signal(signal.SIGTERM, lambda s, f: sigterm_seen.__setitem__(
        0, True))
    engine.install_signal_handlers()
    engine.start()

    futures = []
    fut_lock = threading.Lock()
    stop_clients = threading.Event()

    def feed_at(i):
        rows = 1 + (i % 3)
        rng = np.random.RandomState(2000 + i)
        return {'x': rng.rand(rows, 8).astype('float32')}

    def closed_loop(cid):
        i = 0
        while not stop_clients.is_set():
            fut = engine.submit(feed_at(10000 * (cid + 1) + i),
                                timeout_s=10.0)
            with fut_lock:
                futures.append(fut)
            try:
                res = fut.result(timeout=30.0)
            except TimeoutError:
                return
            if res.status == 'rejected' and res.reason in ('draining',
                                                           'not_ready'):
                return
            i += 1

    clients = [threading.Thread(target=closed_loop, args=(c,), daemon=True)
               for c in range(args.clients)]
    for t in clients:
        t.start()

    # open loop: fixed-rate fire-and-remember
    _harness.stage('traffic')
    period = 1.0 / args.qps if args.qps > 0 else 0.0
    for i in range(args.requests):
        if _faults.active('sigterm') and _faults.fire('sigterm', step=i):
            os.kill(os.getpid(), signal.SIGTERM)   # engine drains, we live
        fut = engine.submit(feed_at(i), timeout_s=args.deadline_ms / 1e3)
        with fut_lock:
            futures.append(fut)
        if period:
            time.sleep(period)
        if engine.breaker.state != 'closed':
            # stretch the tail while tripped so the cooldown elapses
            # with live traffic still flowing — the recovery probe needs
            # a real batch to run against
            time.sleep(0.05)

    def scrape(path='/metrics'):
        import urllib.request
        url = 'http://127.0.0.1:%d%s' % (engine.metrics_port, path)
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return resp.read().decode()

    def prom_values(text):
        out = {}
        for line in text.splitlines():
            if line.startswith('#') or not line.strip():
                continue
            parts = line.split()
            if len(parts) == 2 and '{' not in parts[0]:
                out[parts[0]] = float(parts[1])
        return out

    # mid-soak scrape: the endpoint must be live DURING traffic (an
    # exact accounting identity waits for the post-drain scrape —
    # in-flight requests make it inexact here)
    mid_scrape_ok = None
    if args.metrics_port is not None:
        if engine.metrics_port is None:
            sys.exit('serve_soak: --metrics-port set but the engine did '
                     'not start a metrics server (is PT_OBS=0?)')
        mid_scrape_ok = 'serving_admitted_total' in prom_values(scrape())

    _harness.stage('drain')
    drained = engine.drain()
    stop_clients.set()
    for t in clients:
        t.join(timeout=10.0)

    # ---------------------------------------------------------- audit
    statuses = {}
    no_reply = 0
    with fut_lock:
        all_futs = list(futures)
    for fut in all_futs:
        if not fut.done():
            no_reply += 1
            continue
        res = fut.result(0)
        statuses[res.status] = statuses.get(res.status, 0) + 1

    # the serving block comes straight from the shared schema; p50/p99
    # read the serving.latency_ms bounded histogram (observed only for
    # OK replies — the same population the old in-process list held)
    tel = obs.telemetry_snapshot('serving')
    admitted = tel['admitted']
    terminal = tel['terminal_replies']
    shed_rate = tel['shed_rate']
    p99 = tel['p99_ms']

    rec = {
        'requests_submitted': len(all_futs),
        'statuses': statuses,
        'no_reply': no_reply,
        'sigterm_seen': sigterm_seen[0],
        'drained': bool(drained),
        'state': engine.state,
        'mid_scrape_ok': mid_scrape_ok,
    }
    rec.update(tel)
    print(json.dumps(rec))
    from paddle_tpu.observability import perflab
    perflab.maybe_ledger(
        'serve_soak',
        {'deadlocks': int(rec['deadlocks']), 'no_reply': no_reply,
         'p99_ms': p99,
         'ttft_p99_ms': rec.get('ttft_p99_ms'),
         'itl_p99_ms': rec.get('itl_p99_ms'),
         'scenario': 'oneshot', 'admitted': admitted})

    if args.assert_slo:
        if no_reply:
            sys.exit('serve_soak: %d request(s) never got a terminal '
                     'reply' % no_reply)
        if rec['deadlocks']:
            sys.exit('serve_soak: serving.deadlocks=%d' % rec['deadlocks'])
        if terminal != admitted:
            sys.exit('serve_soak: terminal replies (%d) != admitted (%d) '
                     '— a request was dropped without a reply'
                     % (terminal, admitted))
        if not statuses.get('ok'):
            sys.exit('serve_soak: zero successful requests — no p99 to '
                     'measure')
        if p99 is None or not np.isfinite(p99):
            sys.exit('serve_soak: p99 is not finite: %r' % p99)
        if shed_rate > args.shed_ceiling:
            sys.exit('serve_soak: shed rate %.3f above the ceiling %.3f'
                     % (shed_rate, args.shed_ceiling))
        if not rec['state'] == 'stopped':
            sys.exit('serve_soak: engine did not reach STOPPED '
                     '(state=%s)' % rec['state'])
    if args.expect_breaker:
        if rec['breaker_trips'] < 1 or rec['breaker_recoveries'] < 1:
            sys.exit('serve_soak: breaker trips=%d recoveries=%d — '
                     'expected it to trip AND recover'
                     % (rec['breaker_trips'], rec['breaker_recoveries']))
    if args.expect_drain:
        if not sigterm_seen[0]:
            sys.exit('serve_soak: SIGTERM never chained to the soak '
                     'recorder — drain handler composition broken')
        if not rec['drained']:
            sys.exit('serve_soak: drain did not complete in budget')
        probe = engine.submit({'x': np.ones((1, 8), 'float32')}).result(1)
        if probe.status != 'rejected':
            sys.exit('serve_soak: post-drain submit was not refused '
                     '(%s)' % probe.status)

    # ------------------------------------------- /metrics scrape gate
    if args.metrics_port is not None:
        if not mid_scrape_ok:
            sys.exit('serve_soak: mid-soak /metrics scrape missing '
                     'serving_admitted_total')
        # post-drain the queue is empty, so the scraped identity must
        # be EXACT: every admitted request reached one terminal counter
        pv = prom_values(scrape())
        s_adm = pv.get('serving_admitted_total', -1)
        s_term = (pv.get('serving_completed_total', 0) +
                  pv.get('serving_errors_total', 0) +
                  pv.get('serving_deadline_exceeded_total', 0) +
                  pv.get('serving_shed_total', 0))
        if int(s_adm) != int(s_term):
            sys.exit('serve_soak: scraped accounting identity broken: '
                     'admitted=%d != terminal=%d' % (s_adm, s_term))

    # --------------------------------------------- trace export gate
    if args.trace_out:
        path = obs.export_chrome_trace(args.trace_out)
        with open(path) as f:
            events = json.load(f)['traceEvents']
        ok_tids = [f_.traceparent.split('-')[1] for f_ in all_futs
                   if f_.done() and f_.result(0).status == 'ok'
                   and f_.traceparent]
        if not ok_tids:
            sys.exit('serve_soak: --trace-out with zero ok requests')
        verified = None
        for tid in ok_tids:
            roots = [e for e in events
                     if e.get('name') == 'serving.request'
                     and e.get('args', {}).get('trace_id') == tid]
            if len(roots) != 1:
                sys.exit('serve_soak: trace %s has %d serving.request '
                         'root spans (want exactly 1)' % (tid, len(roots)))
            kids = {e['name']: e for e in events
                    if e.get('name') in ('serving.queue_wait',
                                         'serving.dispatch',
                                         'serving.device')
                    and e.get('args', {}).get('trace_id') == tid}
            if len(kids) != 3:
                continue   # ring may have evicted an early request
            batch_sid = kids['serving.queue_wait']['args']['batch_span_id']
            batches = [e for e in events if e.get('name') == 'serving.batch'
                       and e.get('args', {}).get('span_id') == batch_sid]
            if len(batches) != 1 or \
                    tid not in batches[0]['args'].get('links', ()):
                sys.exit('serve_soak: trace %s: batch span %s missing or '
                         'not linking the request' % (tid, batch_sid))
            covered = sum(k['dur'] for k in kids.values())
            if covered < 0.9 * roots[0]['dur']:
                sys.exit('serve_soak: trace %s: child spans cover %.1f%% '
                         'of the root span (want >= 90%%)'
                         % (tid, 100.0 * covered / max(roots[0]['dur'],
                                                       1e-9)))
            verified = tid
            break
        if verified is None:
            sys.exit('serve_soak: no ok request had a full '
                     'queue/dispatch/device decomposition in the trace')
        print('serve_soak: trace verified for request %s -> %s'
              % (verified, path), file=sys.stderr)

    # ------------------------------------------- flight recorder gate
    if args.expect_flight:
        fdir = _flight.flight_dir()
        if not fdir:
            sys.exit('serve_soak: --expect-flight needs PT_FLIGHT_DIR')
        dumps = sorted(fn for fn in os.listdir(fdir)
                       if fn.startswith('flight_') and fn.endswith('.json'))
        if not dumps:
            sys.exit('serve_soak: no flight dump in %s' % fdir)
        found_batch = found_fault = False
        for fn in dumps:
            with open(os.path.join(fdir, fn)) as f:
                art = json.load(f)
            evs = art.get('events', [])
            found_batch = found_batch or any(
                e.get('name') == 'serving.batch' for e in evs)
            found_fault = found_fault or any(
                e.get('name') == 'fault.injected'
                and e.get('args', {}).get('site') == 'serve_dispatch'
                for e in evs)
        if not (found_batch and found_fault):
            sys.exit('serve_soak: flight dump(s) missing %s' % ', '.join(
                n for n, ok in (('serving.batch span', found_batch),
                                ('serve_dispatch fault event', found_fault))
                if not ok))
    return 0


if __name__ == '__main__':
    _harness.set_tool('SERVE_SOAK')
    _harness.main_guard(main, watchdog_env='PT_SOAK_WATCHDOG_S',
                        flight_tag='serve_soak.watchdog')
